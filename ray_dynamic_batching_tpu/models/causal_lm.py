"""Causal-LM servable models (GPT-2 family, Llama family) on the shared decoder.

BASELINE.json configs 3-4: "GPT-2-medium autoregressive decode (KV-cache,
continuous batching)" and "Llama-3-8B TP=4 over ICI (pjit-sharded replica)".
The engine drives these through two compiled programs — ``prefill`` (one per
(batch, seq) bucket) and ``decode_step`` (one per batch-slot count) — with the
KV cache donated between steps.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)
from ray_dynamic_batching_tpu.models.decoder import (
    DecoderConfig,
    DecoderModule,
    KVCache,
    PagedKVCache,
    decode_mask,
    prefill_mask,
)


class CausalLM(ServableModel):
    family = "causal_lm"

    def __init__(
        self,
        cfg: DecoderConfig,
        name: str,
        dtype: jnp.dtype = jnp.bfloat16,
        kv_dtype: Optional[jnp.dtype] = None,
    ):
        super().__init__(dtype)
        self.name = name
        self.cfg = cfg
        # KV-cache storage dtype (None = activations dtype). int8 halves
        # the decode scan's HBM traffic: codes + per-(token, head) f32
        # scales, quantized at write (models/decoder.py::quantize_kv_rows).
        self.kv_dtype = kv_dtype
        self.module = DecoderModule(cfg, dtype=dtype)

    # --- ServableModel interface (apply == prefill logits for profiling) ---
    def init(self, rng: jax.Array):
        tokens, attn_mask = self.example_inputs(1, 8)
        positions = jnp.arange(8)[None, :]
        mask = prefill_mask(attn_mask)
        return self.module.init(rng, tokens, positions, mask)

    def apply(self, params, tokens: jax.Array, attn_mask: jax.Array) -> jax.Array:
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1])[None, :], tokens.shape
        )
        # token_mask path: attention builds its own causal+padding mask and
        # can route through ring attention under a sequence_parallel context.
        logits, _ = self.module.apply(
            params, tokens, positions, None, token_mask=attn_mask
        )
        return logits

    def apply_with_aux(
        self, params, tokens: jax.Array, attn_mask: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """Forward plus the MoE load-balance auxiliary loss (0 for dense
        models). Training losses must add ``aux_coef * aux`` or the router
        collapses onto one expert and overflow tokens get zeroed."""
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1])[None, :], tokens.shape
        )
        (logits, _), state = self.module.apply(
            params, tokens, positions, None, token_mask=attn_mask,
            mutable=["intermediates"],
        )
        aux_leaves = [
            jnp.asarray(x).sum()
            for x in jax.tree_util.tree_leaves(state.get("intermediates", {}))
        ]
        aux = sum(aux_leaves) if aux_leaves else jnp.zeros((), jnp.float32)
        return logits, aux

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        T = seq_len or 128
        return (
            jnp.zeros((batch_size, T), dtype=jnp.int32),
            jnp.ones((batch_size, T), dtype=jnp.int32),
        )

    # --- decode interface (used by engine.decode) -------------------------
    def make_cache(
        self, batch_size: int, max_len: Optional[int] = None
    ) -> KVCache:
        return KVCache.zeros(
            self.cfg, batch_size, max_len, dtype=self.kv_dtype or self.dtype
        )

    def prefill(
        self, params, tokens: jax.Array, attn_mask: jax.Array, cache: KVCache
    ) -> Tuple[jax.Array, KVCache]:
        """Run the prompt through the model, filling the cache.

        tokens [B, T] right-padded; attn_mask [B, T]. Returns last-valid-token
        logits [B, V] and the cache with ``lengths`` set per row.
        """
        B, T = tokens.shape
        S = cache.capacity
        if T > S:
            raise ValueError(
                f"prompt length {T} exceeds KV-cache capacity {S}; "
                "bucket the prompt or allocate a larger cache"
            )
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        lengths = attn_mask.sum(axis=1).astype(jnp.int32)
        # Queries may attend causally within the prompt; cache positions
        # beyond T are empty, mask them off.
        base = prefill_mask(attn_mask)  # [B,1,T,T]
        if S > T:
            pad = jnp.zeros((B, 1, T, S - T), dtype=bool)
            mask = jnp.concatenate([base, pad], axis=-1)
        else:
            mask = base
        logits, new_cache = self.module.apply(params, tokens, positions, mask, cache)
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0]
        return last, new_cache.replace(lengths=lengths)

    def prefill_chunk(
        self,
        params,
        tokens: jax.Array,     # [B, C] one chunk (last chunk right-padded)
        attn_mask: jax.Array,  # [B, C] 1 = real token
        cache: KVCache,
        start: jax.Array,      # scalar int32: global position of tokens[:,0]
        take_idx: jax.Array,   # scalar int32: logits row to return
    ) -> Tuple[jax.Array, KVCache]:
        """One chunk of a long prompt: write k/v at [start, start+C), attend
        to every cached position up to each token's own. ``start`` and
        ``take_idx`` are TRACED, so one compiled program per chunk width C
        serves every chunk of every prompt — the point is bounding how long
        a single prefill dispatch can stall active decode slots (chunked
        prefill; admission interleaving happens in the engine).

        Caller contract: chunks arrive in order; all chunks are full except
        the last. Padded tail positions write garbage k/v beyond the final
        ``lengths``, which decode masks off exactly as it does for the
        one-shot prefill path. Returns (logits at ``take_idx`` [B, V],
        updated cache) — only the final chunk's call uses the logits.
        """
        B, C = tokens.shape
        S = cache.capacity
        positions = start + jnp.broadcast_to(jnp.arange(C)[None, :], (B, C))
        # Query at global pos p attends cache slots [0, p]: earlier chunks
        # are already resident, in-chunk attention stays causal, and slot 0
        # is always visible so padded query rows keep a sane softmax.
        s_idx = jnp.arange(S)[None, None, None, :]
        mask = s_idx <= positions[:, None, :, None]
        logits, new_cache = self.module.apply(
            params, tokens, positions, mask, cache, write_start=start
        )
        new_lengths = cache.lengths + attn_mask.sum(axis=1).astype(jnp.int32)
        taken = jax.lax.dynamic_slice_in_dim(logits, take_idx, 1, axis=1)
        return taken[:, 0], new_cache.replace(lengths=new_lengths)

    def verify_step(
        self,
        params,
        tokens: jax.Array,   # [B, T] pending token + proposed continuation
        cache: KVCache,
        active: jax.Array,   # [B] bool
    ) -> Tuple[jax.Array, KVCache]:
        """Score a T-token window per row in ONE forward (the speculative-
        verify primitive): row b's window starts at its own ``lengths[b]``,
        k/v scatter per row at those positions, and logits[b, j] scores the
        token AFTER window position j. ``lengths`` are NOT advanced — the
        caller accepts a per-row prefix and sets them. Inactive rows are
        steered out of bounds (writes dropped, logits garbage)."""
        B, T = tokens.shape
        S = cache.capacity
        base = cache.lengths[:, None]  # [B,1]
        positions = base + jnp.arange(T)[None, :]
        # Out-of-bounds positions for inactive/overflowing rows: their
        # scatter is dropped and their outputs are never used.
        positions = jnp.where(
            active[:, None] & (positions < S), positions, S
        )
        s_idx = jnp.arange(S)[None, None, None, :]
        mask = s_idx <= positions[:, None, :, None]
        logits, new_cache = self.module.apply(
            params, tokens, positions, mask, cache, scatter_writes=True
        )
        return logits, new_cache

    def prefill_chunk_paged(
        self,
        params,
        tokens: jax.Array,     # [B, W] one chunk per row (tail right-padded)
        attn_mask: jax.Array,  # [B, W] 1 = real token
        cache: PagedKVCache,
        tables: jax.Array,     # [B, NP] per-row page-table rows
        starts: jax.Array,     # [B] global position of tokens[:, 0] per row
        take_idx: jax.Array,   # [B] per-row logits row to return
    ) -> Tuple[jax.Array, PagedKVCache]:
        """Pages-DIRECT chunked prefill: one chunk of B independent (and
        independently-positioned) prompt fills, written straight through
        per-row page-table rows — no private row cache, no commit copy.
        The speculative-verify primitive generalized to KNOWN tokens: row
        b's chunk occupies global positions ``[starts[b], starts[b]+W)``,
        k/v scatter through ``tables`` into the pages the engine granted
        for this chunk (positions past logical capacity steer to the
        sentinel and DROP — a CoW-borrowed prefix page is below
        ``starts`` by construction and is never written), and attention
        reads the STAIRCASE window (row t attends positions <=
        starts + t — the ``paged_window_mask`` rule with the chunk's
        start as the length; the Tq==1 case is ``decode_mask``). Padded
        tail positions write garbage k/v beyond the final length exactly
        like the slab chunk path; nothing ever attends them.
        ``lengths``/``page_table`` pass through untouched — the caller
        owns both (the engine scatters verified lengths itself at the
        final chunk). Returns (logits at ``take_idx`` [B, V], cache)."""
        B, W = tokens.shape
        S = tables.shape[1] * cache.page_size
        positions = starts[:, None] + jnp.broadcast_to(
            jnp.arange(W)[None, :], (B, W)
        )
        # Overflowing positions (an unaligned continuation's padded tail
        # can run past logical capacity) steer to S: their scatter drops
        # at the sentinel and their outputs are never taken.
        positions = jnp.where(positions < S, positions, S)
        logits, new_cache = self.module.apply(
            params, tokens, positions, None, cache, scatter_writes=True,
            page_table=tables, kv_lengths=starts,
        )
        taken = jnp.take_along_axis(
            logits, take_idx[:, None, None], axis=1
        )[:, 0]
        return taken, new_cache

    def verify_step_paged(
        self,
        params,
        tokens: jax.Array,   # [B, T] pending token + proposed continuation
        cache: PagedKVCache,
        active: jax.Array,   # [B] bool
    ) -> Tuple[jax.Array, PagedKVCache]:
        """Paged mirror of :meth:`verify_step` — the speculative-verify
        primitive over the page pool. Row b's T-token window starts at
        its own ``lengths[b]``; k/v scatter through the page table into
        the round's scratch pages (per-row positions, ``mode="drop"``
        for rows steered out of bounds), and attention reads the
        STAIRCASE window (row t attends positions <= lengths + t — the
        ``paged_window_mask`` rule, fused in the paged kernel and
        streamed by the gather fallback). ``lengths`` are NOT advanced —
        the caller accepts a per-row prefix and sets them, exactly the
        slab contract, which is what keeps paged+spec greedy decoding
        byte-identical to slab+spec."""
        B, T = tokens.shape
        S = cache.capacity
        base = cache.lengths[:, None]  # [B, 1]
        positions = base + jnp.arange(T)[None, :]
        # Out-of-bounds positions for inactive/overflowing rows: their
        # scatter steers to the sentinel page and their outputs are
        # never accepted (the engine clamps n_out to remaining room).
        positions = jnp.where(
            active[:, None] & (positions < S), positions, S
        )
        logits, new_cache = self.module.apply(
            params, tokens, positions, None, cache, scatter_writes=True,
            page_table=cache.page_table, kv_lengths=cache.lengths,
        )
        return logits, new_cache

    def decode_step(
        self,
        params,
        tokens: jax.Array,   # [B, 1] current token per slot
        cache: KVCache,
        active: jax.Array,   # [B] bool — which slots advance
    ) -> Tuple[jax.Array, KVCache]:
        """One decode step for all slots; returns logits [B, V] + new cache.

        Rows whose cache is full are force-deactivated: their out-of-bounds
        scatter is explicitly dropped (decoder writes with mode="drop"), their
        logits are garbage, and ``lengths`` stops advancing at capacity, so
        the engine detects exhaustion via ``lengths == capacity`` instead of
        silently decoding on (or corrupting the last cache slot).
        """
        in_bounds = cache.lengths < cache.capacity
        active = jnp.logical_and(active, in_bounds)
        positions = cache.lengths[:, None]
        mask = decode_mask(cache.lengths, cache.capacity)
        logits, new_cache = self.module.apply(params, tokens, positions, mask, cache)
        new_lengths = cache.lengths + active.astype(jnp.int32)
        return logits[:, 0], new_cache.replace(lengths=new_lengths)

    def make_paged_cache(
        self, batch_size: int, num_pages: int, page_size: int,
        max_len: int,
    ) -> PagedKVCache:
        """A paged KV pool: ``num_pages`` fixed HBM pages + a
        ``[batch_size, max_len // page_size]`` page table (engine-owned
        allocation — ``engine/paging.py``)."""
        return PagedKVCache.zeros(
            self.cfg, batch_size, num_pages, page_size, max_len,
            dtype=self.kv_dtype or self.dtype,
        )

    def decode_step_paged(
        self,
        params,
        tokens: jax.Array,   # [B, 1] current token per slot
        cache: PagedKVCache,
        active: jax.Array,   # [B] bool — which slots advance
    ) -> Tuple[jax.Array, PagedKVCache]:
        """One decode step against the paged pool — the exact
        :meth:`decode_step` contract (force-deactivation at logical
        capacity, lengths advance only for active rows, garbage logits
        on inactive rows) with writes and reads routed through the page
        table. Token-exact vs the slab step by construction: the write
        rule maps the same logical position to a physical (page,
        offset), and attention sees the same positions <= lengths window
        through the dispatcher's paged gather/kernel."""
        in_bounds = cache.lengths < cache.capacity
        active = jnp.logical_and(active, in_bounds)
        positions = cache.lengths[:, None]
        logits, new_cache = self.module.apply(
            params, tokens, positions, None, cache,
            page_table=cache.page_table, kv_lengths=cache.lengths,
        )
        new_lengths = cache.lengths + active.astype(jnp.int32)
        return logits[:, 0], new_cache.replace(lengths=new_lengths)

    # --- planning ---------------------------------------------------------
    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        T = seq_len or 128
        c = self.cfg
        per_tok = 2 * (
            c.d_model * c.head_dim * (c.num_heads + 2 * c.num_kv_heads)
            + c.num_heads * c.head_dim * c.d_model
            + (3 if c.gated_mlp else 2) * c.d_model * c.mlp_dim
        )
        attn = 4 * T * c.d_model  # score+value flops per token, avg T/2 ctx * 2
        return c.num_layers * (per_tok + attn) * T + 2 * c.d_model * c.vocab_size * T

    def kv_bytes_per_slot(self, max_len: Optional[int] = None) -> int:
        c = self.cfg
        S = max_len or c.max_seq_len
        itemsize = jnp.dtype(self.kv_dtype or self.dtype).itemsize
        per_row = c.head_dim * itemsize
        if self.kv_dtype is not None and jnp.dtype(
                self.kv_dtype) == jnp.dtype(jnp.int8):
            per_row += 4  # one f32 scale per cached (token, head) row
        return 2 * c.num_layers * S * c.num_kv_heads * per_row

    def sharding_rules(self):
        return [
            (r"/q/kernel", P(None, "tp", None)),
            (r"/k/kernel", P(None, "tp", None)),
            (r"/v/kernel", P(None, "tp", None)),
            (r"/o/kernel", P("tp", None, None)),
            (r"mlp_gate/kernel", P(None, "tp")),
            (r"mlp_up/kernel", P(None, "tp")),
            (r"mlp_down/kernel", P("tp", None)),
            (r"moe/wi", P("ep", None, "tp")),
            (r"moe/wg", P("ep", None, "tp")),
            (r"moe/wo", P("ep", "tp", None)),
            (r"tok_embed/embedding", P("tp", None)),
            (r"lm_head/kernel", P(None, "tp")),
        ]

    def cache_pspec(self) -> KVCache:
        """PartitionSpecs for the KV cache (kv heads sharded over tp)."""
        scale_spec = None
        if self.kv_dtype is not None and jnp.dtype(
                self.kv_dtype) == jnp.dtype(jnp.int8):
            scale_spec = P(None, None, None, "tp")
        return KVCache(
            k=P(None, None, None, "tp", None),   # type: ignore[arg-type]
            v=P(None, None, None, "tp", None),   # type: ignore[arg-type]
            lengths=P(None),                      # type: ignore[arg-type]
            k_scale=scale_spec,                   # type: ignore[arg-type]
            v_scale=scale_spec,                   # type: ignore[arg-type]
        )

    def paged_cache_pspec(self) -> PagedKVCache:
        """PartitionSpecs for the PAGED KV pool (ROADMAP item 2): pages
        shard on the kv-head dim exactly like the slab cache — the pool
        is ``[L, P, ps, K, H]``, so K sits at the same index 3 and a
        shard owns the full page set for its head slice. The page table
        and lengths REPLICATE: page indices are shard-invariant (every
        shard's slice of page ``p`` backs the same logical positions),
        which is what lets the host-side ``PageAllocator`` stay
        replica-global. Scale planes (``[L, P, ps, K]``) shard with
        their heads."""
        scale_spec = None
        if self.kv_dtype is not None and jnp.dtype(
                self.kv_dtype) == jnp.dtype(jnp.int8):
            scale_spec = P(None, None, None, "tp")
        return PagedKVCache(
            k=P(None, None, None, "tp", None),   # type: ignore[arg-type]
            v=P(None, None, None, "tp", None),   # type: ignore[arg-type]
            page_table=P(None, None),             # type: ignore[arg-type]
            lengths=P(None),                      # type: ignore[arg-type]
            k_scale=scale_spec,                   # type: ignore[arg-type]
            v_scale=scale_spec,                   # type: ignore[arg-type]
        )


GPT2_MEDIUM = DecoderConfig(
    vocab_size=50257,
    d_model=1024,
    num_layers=24,
    num_heads=16,
    num_kv_heads=16,
    mlp_dim=4096,
    max_seq_len=1024,
    pos="learned",
    norm="ln",
    gated_mlp=False,
    use_bias=True,
    tie_embeddings=True,
)

LLAMA3_8B = DecoderConfig(
    vocab_size=128256,
    d_model=4096,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    mlp_dim=14336,
    max_seq_len=8192,
    pos="rope",
    norm="rms",
    gated_mlp=True,
    use_bias=False,
    rope_theta=500000.0,
)

# Draft companion for gpt2_medium (ISSUE 13 bench A/B): same vocab and
# position style so its proposals index the target's logit space, ~1/40
# of the FLOPs — the Leviathan-shaped draft geometry. Random-init
# weights make on-chip acceptance ~0 (the captured row then measures the
# bounded-degradation floor, honestly stamped via spec_acceptance);
# trained weights turn the same arm into the speedup measurement.
GPT2_DRAFT = DecoderConfig(
    vocab_size=50257,
    d_model=256,
    num_layers=4,
    num_heads=4,
    num_kv_heads=4,
    mlp_dim=1024,
    max_seq_len=1024,
    pos="learned",
    norm="ln",
    gated_mlp=False,
    use_bias=True,
    tie_embeddings=True,
)

TINY_LM = DecoderConfig(
    vocab_size=512,
    d_model=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    mlp_dim=128,
    max_seq_len=256,
)

TINY_MOE = DecoderConfig(
    vocab_size=512,
    d_model=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    mlp_dim=128,
    max_seq_len=256,
    num_experts=4,
    moe_top_k=2,
)


@register_model("gpt2_medium", slo=ModelSLO(latency_slo_ms=500.0))
def _gpt2_medium(**kwargs) -> CausalLM:
    return CausalLM(GPT2_MEDIUM, name="gpt2_medium", **kwargs)


@register_model("llama3_8b", slo=ModelSLO(latency_slo_ms=150.0))
def _llama3_8b(**kwargs) -> CausalLM:
    return CausalLM(LLAMA3_8B, name="llama3_8b", **kwargs)


@register_model("gpt2_draft")
def _gpt2_draft(**kwargs) -> CausalLM:
    return CausalLM(GPT2_DRAFT, name="gpt2_draft", **kwargs)


@register_model("llama_tiny")
def _llama_tiny(**kwargs) -> CausalLM:
    return CausalLM(TINY_LM, name="llama_tiny", **kwargs)


@register_model("llama_tiny_int8kv")
def _llama_tiny_int8kv(**kwargs) -> CausalLM:
    """llama_tiny with the int8 KV cache — a DISTINCT registry name so
    its decode/prefill tables land beside (not over) the bf16 ones:
    quantized engines must plan from tables measured at their own cache
    dtype (plan_from_tables docstring)."""
    kwargs.setdefault("kv_dtype", jnp.int8)
    return CausalLM(TINY_LM, name="llama_tiny_int8kv", **kwargs)


@register_model("moe_tiny")
def _moe_tiny(**kwargs) -> CausalLM:
    return CausalLM(TINY_MOE, name="moe_tiny", **kwargs)
