"""Mixture-of-experts FFN block with expert parallelism over the ``ep`` axis.

Expert parallelism is absent from the reference (SURVEY.md §2.4 lists EP as
a from-scratch TPU design item). TPU-first design: GShard-style capacity-based
dispatch expressed as dense one-hot einsums — every shape static, so the
whole block jits once — with expert weights carrying a leading expert dim
sharded over the ``ep`` mesh axis. Under GSPMD the dispatched-token tensor is
sharding-constrained to ``ep``, which makes XLA insert the all_to_all pair
(dispatch/combine) over ICI rather than gathering all tokens everywhere.

Top-k routing (renormalized), per-row capacity C = ceil(k*T/E * capacity
factor); overflow tokens fall through the residual connection (standard
GShard behavior — bounded memory beats tail-token coverage on TPU). The
load-balance auxiliary loss is sown under ``intermediates/moe_aux_loss``.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """Sharding constraint that degrades to a no-op when no mesh is in
    context (single-device eager tests) or a dim isn't divisible by its
    mesh axis (e.g. batch-of-1 init under a dp>1 mesh)."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (RuntimeError, ValueError):
        return x


class MoEBlock(nn.Module):
    d_model: int
    mlp_dim: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    gated: bool = True  # SwiGLU experts (matches the dense MLP family)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:  # [B, T, D]
        B, T, D = x.shape
        E, F, k = self.num_experts, self.mlp_dim, self.top_k
        C = max(1, math.ceil(k * T / E * self.capacity_factor))

        router = nn.Dense(
            E, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            name="router",
        )
        gates = jax.nn.softmax(router(x.astype(jnp.float32)), axis=-1)  # [B,T,E]

        # top-k gate selection, renormalized over the chosen experts
        top_gates, top_idx = jax.lax.top_k(gates, k)          # [B,T,k]
        top_gates = top_gates / jnp.maximum(
            top_gates.sum(axis=-1, keepdims=True), 1e-9
        )

        # position of each (token, choice) within its expert's capacity
        # buffer: running count of prior assignments to the same expert,
        # choice-major priority (all first choices beat all second choices)
        choice_onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B,T,k,E]
        flat = choice_onehot.transpose(0, 2, 1, 3).reshape(B, k * T, E)
        pos_flat = jnp.cumsum(flat, axis=1) - flat             # [B,kT,E]
        pos_in_expert = pos_flat.reshape(B, k, T, E).transpose(0, 2, 1, 3)
        within_cap = pos_in_expert < C                          # [B,T,k,E]

        # dispatch [B,T,E,C]: one-hot over capacity slots; overflow tokens
        # get an out-of-range index -> all-zero row (fall through residual)
        cap_idx = jnp.where(within_cap, pos_in_expert, C)
        cap_onehot = jax.nn.one_hot(cap_idx, C, dtype=jnp.float32)  # [B,T,k,E,C]
        dispatch = jnp.einsum(
            "btke,btkec->btec", choice_onehot, cap_onehot
        )
        gate_per_expert = jnp.einsum("btke,btk->bte", choice_onehot, top_gates)
        combine = dispatch * gate_per_expert[..., None]

        # expert weights: leading expert dim sharded over ep, F over tp
        init = nn.initializers.lecun_normal()
        wi = self.param("wi", init, (E, D, F), jnp.float32)
        wo = self.param("wo", init, (E, F, D), jnp.float32)
        if self.gated:
            wg = self.param("wg", init, (E, D, F), jnp.float32)

        xe = jnp.einsum("btec,btd->becd", dispatch, x.astype(jnp.float32))
        # all_to_all: tokens move to their expert's devices
        xe = _constrain(xe, P("dp", "ep", None, None))
        xe = xe.astype(self.dtype)
        h = jnp.einsum("becd,edf->becf", xe, wi.astype(self.dtype))
        if self.gated:
            g = jnp.einsum("becd,edf->becf", xe, wg.astype(self.dtype))
            h = nn.silu(g) * h
        else:
            h = nn.gelu(h)
        ye = jnp.einsum("becf,efd->becd", h, wo.astype(self.dtype))
        ye = _constrain(ye, P("dp", "ep", None, None))
        y = jnp.einsum("btec,becd->btd", combine, ye.astype(jnp.float32))

        # load-balance aux loss (Shazeer/GShard): E * sum_e f_e * p_e
        density = choice_onehot[:, :, 0].mean(axis=1)   # top-1 assignment frac
        mean_gate = gates.mean(axis=1)                   # [B,E]
        aux = (density * mean_gate).sum(axis=-1).mean() * E
        self.sow("intermediates", "moe_aux_loss", aux)
        return y.astype(x.dtype)
