"""Shared causal-decoder transformer with explicit functional KV cache.

New capability relative to the reference (which serves single-shot vision
models — SURVEY.md section 7 stage 7): autoregressive decode for the
BASELINE.json GPT-2/Llama configs. TPU-first design decisions:

- The KV cache is an explicit pytree argument returned updated from every
  step, so the engine can ``jit(..., donate_argnums=...)`` and XLA updates it
  in place in HBM (no realloc per token).
- Fixed-capacity caches + scatter-at-``lengths`` writes keep every shape
  static; continuous batching varies *contents*, never shapes, so one compiled
  program serves the whole decode stream.
- Attention flows through :mod:`ops.attention` (Pallas-fused on TPU).
- GQA (``num_kv_heads < num_heads``) shrinks cache HBM traffic — the decode
  bottleneck is HBM bandwidth, not MXU FLOPs.

One config-driven module covers both model families (learned-pos/LN/GeLU for
GPT-2; RoPE/RMSNorm/gated-SiLU/GQA for Llama).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.struct import dataclass as pytree_dataclass

from ray_dynamic_batching_tpu.ops import attention as attn_ops


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int
    d_model: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    mlp_dim: int
    max_seq_len: int = 2048
    pos: str = "rope"  # "rope" | "learned"
    norm: str = "rms"  # "rms" | "ln"
    gated_mlp: bool = True  # SwiGLU vs plain GeLU MLP
    use_bias: bool = False
    tie_embeddings: bool = False
    rope_theta: float = 10000.0
    num_experts: int = 0      # > 0 switches the MLP to a MoE block (ep axis)
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


@pytree_dataclass
class KVCache:
    """Per-model cache: k/v [L, B, S, K, H]; lengths [B] = valid prefix.

    With ``dtype=int8`` the cache is weight-free quantized storage:
    k/v hold int8 codes and ``k_scale``/``v_scale`` [L, B, S, K] f32
    hold one scale per cached (token, head) row (absmax/127, computed
    at write). The guaranteed win is CAPACITY: half the HBM per slot,
    so auto-sizing fits ~2x the slots per chip. The bandwidth win on
    the decode scan (its dominant HBM traffic) is realized where the
    dequant fuses into the attention read; the XLA fallback path
    materializes a dequantized operand, trading scan bandwidth for
    capacity. Scales are pytree fields: donation and sharding treat
    them as part of the cache automatically; the row seed/extract paths
    (admission copies, prefix/session segments) thread them explicitly
    as part of every stored segment tuple."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @staticmethod
    def zeros(
        cfg: DecoderConfig, batch_size: int, max_len: Optional[int] = None,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        S = max_len or cfg.max_seq_len
        shape = (cfg.num_layers, batch_size, S, cfg.num_kv_heads, cfg.head_dim)
        quantized = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
        return KVCache(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((batch_size,), dtype=jnp.int32),
            k_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
            v_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
        )

    @property
    def capacity(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


@pytree_dataclass
class PagedKVCache:
    """Paged KV pool: k/v ``[L, P, page_size, K, H]`` fixed HBM pages,
    gathered per slot through ``page_table`` ``[B, NP]`` int32 (entry j
    names the physical page backing logical positions
    ``[j*page_size, (j+1)*page_size)`` of that slot; unallocated entries
    carry the sentinel ``P`` — one past the last page — so writes
    through them drop and gathers clamp into masked territory).

    The slab cache gives every slot a private ``max_len`` KV run whether
    it uses 3 tokens or 300; here HBM occupancy follows *actual* cached
    tokens at page granularity, prefix/session reuse shares pages by
    refcount instead of copying rows (``engine/paging.py``), and EOS
    returns pages to the free list mid-cycle. Shapes stay fully static —
    continuous batching still varies contents, never shapes — so the
    one-compiled-program-per-stream property of the slab path survives.

    Quantized pools mirror the slab layout: k/v hold int8 codes,
    ``k_scale``/``v_scale`` ``[L, P, page_size, K]`` hold the per-row
    f32 scales, paged with the SAME page table."""

    k: jax.Array
    v: jax.Array
    page_table: jax.Array  # [B, NP] int32, sentinel P = unallocated
    lengths: jax.Array     # [B] valid logical prefix per slot
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @staticmethod
    def zeros(
        cfg: DecoderConfig, batch_size: int, num_pages: int,
        page_size: int, max_len: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "PagedKVCache":
        if max_len % page_size != 0:
            raise ValueError(
                f"max_len {max_len} must be a multiple of page_size "
                f"{page_size} (logical capacity is whole pages)"
            )
        n_entries = max_len // page_size
        shape = (cfg.num_layers, num_pages, page_size,
                 cfg.num_kv_heads, cfg.head_dim)
        quantized = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
        return PagedKVCache(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            page_table=jnp.full((batch_size, n_entries), num_pages,
                                dtype=jnp.int32),
            lengths=jnp.zeros((batch_size,), dtype=jnp.int32),
            k_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
            v_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
        )

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def capacity(self) -> int:
        """Per-slot LOGICAL capacity (page_table width x page size) —
        the same contract as ``KVCache.capacity``."""
        return self.page_table.shape[1] * self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def quantize_kv_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-(token, head) absmax int8 quantization: x [..., H] ->
    (codes int8 [..., H], scale f32 [...])."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    codes = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return codes, scale


def dequantize_kv(codes: jax.Array, scale: jax.Array,
                  dtype: jnp.dtype) -> jax.Array:
    """codes int8 [..., H] * scale [...] -> [..., H] in ``dtype``.
    Single source of the dequant rule — the attention dispatcher's
    fallback path uses this exact function, so kernel-vs-fallback
    parity cannot drift."""
    return (codes.astype(jnp.float32) * scale[..., None]).astype(dtype)


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0
) -> jax.Array:
    """Rotary embedding. x [B, T, N, H], positions [B, T]."""
    H = x.shape[-1]
    half = H // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class DecoderLayer(nn.Module):
    cfg: DecoderConfig
    dtype: Any = jnp.bfloat16

    def _norm(self, name: str):
        if self.cfg.norm == "rms":
            return RMSNorm(name=name)
        return nn.LayerNorm(dtype=jnp.float32, name=name)

    @nn.compact
    def __call__(
        self,
        x: jax.Array,               # [B, T, D]
        positions: jax.Array,       # [B, T]
        mask: Optional[jax.Array],  # [B, 1, T, S_attended] True = attend
        cache_kv: Optional[Tuple[jax.Array, jax.Array]] = None,  # k/v [L,B,S,K,H]
        token_mask: Optional[jax.Array] = None,  # [B, T] (no-cache path)
        layer_idx: int = 0,
        write_start: Optional[jax.Array] = None,  # scalar: chunk write offset
        scatter_writes: bool = False,  # per-row writes at ``positions``
        page_table: Optional[jax.Array] = None,  # [B, NP]: paged decode
        kv_lengths: Optional[jax.Array] = None,  # [B] paged validity bound
    ) -> Tuple[jax.Array, Optional[Tuple[jax.Array, jax.Array]]]:
        cfg = self.cfg
        dense = lambda feats, name, axis=-1: nn.DenseGeneral(  # noqa: E731
            feats,
            axis=axis,
            use_bias=cfg.use_bias,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        y = self._norm("attn_norm")(x).astype(self.dtype)
        q = dense((cfg.num_heads, cfg.head_dim), "q")(y)
        k = dense((cfg.num_kv_heads, cfg.head_dim), "k")(y)
        v = dense((cfg.num_kv_heads, cfg.head_dim), "v")(y)
        if cfg.pos == "rope":
            q = apply_rope(q, positions, cfg.rope_theta)
            k = apply_rope(k, positions, cfg.rope_theta)

        if cache_kv is not None:
            # The layer scatters into the FULL stacked [L, B, S, K, H] cache
            # at its own layer index and hands the whole buffer to the next
            # layer. Never slice-out/re-stack per layer: rebuilding the
            # stacked array every decode step forces XLA to materialize a
            # fresh multi-GB copy per token (measured 15 ms/substep for
            # GPT-2-medium at 32 slots vs ~2 ms with in-place updates).
            # A 4-tuple carries the int8 cache's per-row scales; every
            # write path scatters codes and scales with the SAME indices.
            quantized = len(cache_kv) == 4
            if quantized:
                k_full, v_full, ks_full, vs_full = cache_kv
                k_w, k_s = quantize_kv_rows(k)
                v_w, v_s = quantize_kv_rows(v)
            else:
                k_full, v_full = cache_kv
                ks_full = vs_full = None
                k_w, v_w = k, v
            B, T = positions.shape
            if page_table is not None:
                # Paged writes: the cache arrays are page POOLS
                # [L, P, ps, K, H]; each token's logical position maps
                # through the slot's page-table row to a physical
                # (page, offset). Two patterns share the rule — plain
                # decode (T == 1, positions = lengths) and the
                # speculative-verify window (``scatter_writes``: T ==
                # k+1 per-row positions starting at each slot's own
                # length, landing in the round's scratch pages).
                # Unallocated entries carry the sentinel P, and
                # logically-overflowing rows are steered to it too, so
                # mode="drop" voids exactly the writes the slab path's
                # out-of-bounds scatter voids.
                if T != 1 and not scatter_writes:
                    raise NotImplementedError(
                        "paged cache writes support single-token decode "
                        "and per-row scatter windows (spec verify) only; "
                        "prefill runs on row caches and commits through "
                        "the engine's page scatter"
                    )
                P = k_full.shape[1]
                ps = k_full.shape[2]
                n_entries = page_table.shape[1]
                idx = positions  # [B, T]
                rows = jnp.arange(B)[:, None]
                pidx = jnp.minimum(idx // ps, n_entries - 1)
                pid = jnp.where(
                    idx < n_entries * ps, page_table[rows, pidx], P
                )
                off = idx % ps
                k_full = k_full.at[layer_idx, pid, off].set(
                    k_w, mode="drop"
                )
                v_full = v_full.at[layer_idx, pid, off].set(
                    v_w, mode="drop"
                )
                if quantized:
                    ks_full = ks_full.at[layer_idx, pid, off].set(
                        k_s, mode="drop"
                    )
                    vs_full = vs_full.at[layer_idx, pid, off].set(
                        v_s, mode="drop"
                    )
            elif scatter_writes:
                # Batched multi-token writes at PER-ROW positions (the
                # speculative-verify path: each slot's window starts at its
                # own length). mode="drop" voids rows steered out of
                # bounds, exactly like the single-token decode scatter.
                rows = jnp.arange(B)[:, None]
                k_full = k_full.at[layer_idx, rows, positions].set(
                    k_w, mode="drop"
                )
                v_full = v_full.at[layer_idx, rows, positions].set(
                    v_w, mode="drop"
                )
                if quantized:
                    ks_full = ks_full.at[layer_idx, rows, positions].set(
                        k_s, mode="drop"
                    )
                    vs_full = vs_full.at[layer_idx, rows, positions].set(
                        v_s, mode="drop"
                    )
            elif T == 1:
                # Decode: scatter this token's k/v at its row position.
                # mode="drop" makes a full row's out-of-bounds write a no-op
                # instead of clamping onto (and corrupting) the last slot.
                idx = positions[:, 0]
                rows = jnp.arange(B)
                k_full = k_full.at[layer_idx, rows, idx].set(
                    k_w[:, 0], mode="drop"
                )
                v_full = v_full.at[layer_idx, rows, idx].set(
                    v_w[:, 0], mode="drop"
                )
                if quantized:
                    ks_full = ks_full.at[layer_idx, rows, idx].set(
                        k_s[:, 0], mode="drop"
                    )
                    vs_full = vs_full.at[layer_idx, rows, idx].set(
                        v_s[:, 0], mode="drop"
                    )
            else:
                # Prefill: contiguous write at offset 0, or — for chunked
                # prefill of long prompts — at a TRACED start position, so
                # one compiled program serves every chunk of the prompt
                # (dynamic start, static chunk shape).
                start = write_start if write_start is not None else 0
                k_full = jax.lax.dynamic_update_slice(
                    k_full, k_w[None], (layer_idx, 0, start, 0, 0)
                )
                v_full = jax.lax.dynamic_update_slice(
                    v_full, v_w[None], (layer_idx, 0, start, 0, 0)
                )
                if quantized:
                    ks_full = jax.lax.dynamic_update_slice(
                        ks_full, k_s[None], (layer_idx, 0, start, 0)
                    )
                    vs_full = jax.lax.dynamic_update_slice(
                        vs_full, v_s[None], (layer_idx, 0, start, 0)
                    )
            # Quantized caches hand CODES + scales to the dispatcher:
            # the decode kernel scans the 1-byte codes directly (the
            # bandwidth win); non-kernel paths dequantize there.
            scale_kwargs = {}
            if quantized:
                scale_kwargs = {"k_scale": ks_full[layer_idx],
                                "v_scale": vs_full[layer_idx]}
                new_cache = (k_full, v_full, ks_full, vs_full)
            else:
                new_cache = (k_full, v_full)
            if page_table is not None:
                # Paged read: k/v are the page pools; the dispatcher
                # gathers through the table (fused in the Pallas paged
                # kernel; an explicit gather + the shared decode mask on
                # the fallback — one mask rule, token-exact either way).
                scale_kwargs.update(page_table=page_table,
                                    kv_lengths=kv_lengths)
            attn_out = attn_ops.dot_product_attention(
                q, k_full[layer_idx], v_full[layer_idx], mask=mask,
                **scale_kwargs,
            )
        elif token_mask is not None:
            # Full-sequence self-attention: routes through ring attention
            # over the sp mesh axis under a sequence_parallel context.
            attn_out = attn_ops.self_attention(q, k, v, token_mask, causal=True)
            new_cache = None
        else:
            attn_out = attn_ops.dot_product_attention(q, k, v, mask=mask)
            new_cache = None

        attn_out = dense(cfg.d_model, "o", axis=(-2, -1))(attn_out)
        x = x + attn_out

        y = self._norm("mlp_norm")(x).astype(self.dtype)
        if cfg.num_experts > 0:
            from ray_dynamic_batching_tpu.models.moe import MoEBlock

            y = MoEBlock(
                d_model=cfg.d_model,
                mlp_dim=cfg.mlp_dim,
                num_experts=cfg.num_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                gated=cfg.gated_mlp,
                dtype=self.dtype,
                name="moe",
            )(y)
        elif cfg.gated_mlp:
            gate = dense(cfg.mlp_dim, "mlp_gate")(y)
            up = dense(cfg.mlp_dim, "mlp_up")(y)
            y = nn.silu(gate) * up
            y = dense(cfg.d_model, "mlp_down")(y)
        else:
            y = nn.gelu(dense(cfg.mlp_dim, "mlp_up")(y))
            y = dense(cfg.d_model, "mlp_down")(y)
        return x + y, new_cache


class DecoderModule(nn.Module):
    cfg: DecoderConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,          # [B, T]
        positions: jax.Array,       # [B, T]
        mask: Optional[jax.Array],  # [B, 1, T, S]
        cache: Optional[KVCache] = None,
        token_mask: Optional[jax.Array] = None,  # [B, T] (no-cache path)
        write_start: Optional[jax.Array] = None,  # scalar chunk offset
        scatter_writes: bool = False,  # per-row multi-token cache writes
        page_table: Optional[jax.Array] = None,  # paged decode (T == 1)
        kv_lengths: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, Optional[KVCache]]:
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="tok_embed",
        )
        x = embed(tokens)
        if cfg.pos == "learned":
            pos_embed = nn.Embed(
                cfg.max_seq_len,
                cfg.d_model,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="pos_embed",
            )
            x = x + pos_embed(positions)

        cache_kv = None
        if cache is not None:
            cache_kv = (
                (cache.k, cache.v, cache.k_scale, cache.v_scale)
                if cache.quantized else (cache.k, cache.v)
            )
        for i in range(cfg.num_layers):
            x, updated = DecoderLayer(cfg, dtype=self.dtype, name=f"layer{i}")(
                x, positions, mask, cache_kv, token_mask, layer_idx=i,
                write_start=write_start, scatter_writes=scatter_writes,
                page_table=page_table, kv_lengths=kv_lengths,
            )
            if updated is not None:
                cache_kv = updated

        if cfg.norm == "rms":
            x = RMSNorm(name="final_norm")(x)
        else:
            x = nn.LayerNorm(dtype=jnp.float32, name="final_norm")(x)

        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(
                cfg.vocab_size,
                use_bias=False,
                dtype=jnp.float32,
                param_dtype=jnp.float32,
                name="lm_head",
            )(x)

        out_cache = None
        if cache is not None:
            scales = dict(
                k_scale=cache_kv[2] if len(cache_kv) == 4 else None,
                v_scale=cache_kv[3] if len(cache_kv) == 4 else None,
            )
            if page_table is not None:
                out_cache = PagedKVCache(
                    k=cache_kv[0], v=cache_kv[1], page_table=page_table,
                    lengths=cache.lengths, **scales,
                )
            else:
                out_cache = KVCache(
                    k=cache_kv[0], v=cache_kv[1], lengths=cache.lengths,
                    **scales,
                )
        return logits, out_cache


def prefill_mask(attn_mask: jax.Array) -> jax.Array:
    """Causal mask limited to valid tokens. attn_mask [B, T] -> [B, 1, T, T]."""
    T = attn_mask.shape[1]
    causal = jnp.tril(jnp.ones((T, T), dtype=bool))
    valid = attn_mask[:, None, None, :].astype(bool)
    return causal[None, None, :, :] & valid


def decode_mask(lengths: jax.Array, capacity: int) -> jax.Array:
    """Attend to positions [0, lengths] inclusive. lengths [B] -> [B,1,1,S]."""
    pos = jnp.arange(capacity)[None, None, None, :]
    return pos <= lengths[:, None, None, None]


def paged_window_mask(lengths: jax.Array, capacity: int,
                      window: int) -> jax.Array:
    """STAIRCASE window over the paged logical view: verify-window row t
    (the token written at position ``lengths + t``) attends positions
    [0, lengths + t] inclusive. lengths [B] -> [B, 1, window, S].

    This is THE paged window rule — the Pallas paged kernel computes the
    same staircase in-kernel from the prefetched lengths, and the gather
    fallback streams this mask — so kernel and fallback can never
    disagree about what a spec-verify row may attend. ``window == 1`` is
    exactly :func:`decode_mask` (plain paged decode)."""
    pos = jnp.arange(capacity)[None, None, None, :]
    bound = (lengths[:, None] + jnp.arange(window)[None, :])
    return pos <= bound[:, None, :, None]
