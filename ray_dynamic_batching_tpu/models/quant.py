"""Weight-only int8 quantization for serving params.

Decode is HBM-bandwidth bound: every step streams the full weight set
through the MXU for one token per slot, so weight bytes — not FLOPs — set
tokens/s. Symmetric per-output-channel int8 on the matmul kernels halves
that traffic vs bf16 (weights live in HBM as int8; the in-jit dequantize
is a convert+scale XLA fuses into the consuming matmul, not a
materialized bf16 copy). Embeddings, norms, and biases stay in their
original dtype — they are a rounding-sensitive sliver of the bytes.

The quantized tree is a drop-in params pytree whose kernel leaves are
:class:`QTensor` nodes; ``dequantize_tree`` (called INSIDE jit by the
engine) rebuilds a standard tree for the unmodified flax modules. No
model-code changes, no custom matmul kernels: the compiler owns fusion,
exactly the stance SURVEY §7 takes for everything else on this path.

The reference has no quantization story (fp16 autocast only,
``293-project/profiling/ModelProfiler.py``); this is a TPU-serving
addition. Accuracy is the standard weight-only trade: logits drift by
O(1/127) relative error per channel; greedy decodes of well-trained
models rarely flip. Throughput claims require on-chip measurement —
the knob ships measured-off by default.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax.struct import dataclass as pytree_dataclass

# Kernels with >= this many elements quantize; tiny leaves (norm scales,
# biases) are not worth the metadata.
_MIN_QUANT_ELEMS = 1024


@pytree_dataclass
class QTensor:
    """Symmetric per-output-channel int8 weight: ``w ~= q * scale``.

    ``q`` int8, same shape as the original kernel; ``scale`` float32,
    shaped like the kernel with every axis but the LAST reduced to 1
    (flax kernels put output features last)."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(dtype) * self.scale.astype(dtype))


def _quantize_leaf(w: jax.Array) -> QTensor:
    reduce_axes = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                     keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q=q.astype(jnp.int8), scale=scale.astype(jnp.float32))


def _wants_quant(path: Tuple, leaf: Any) -> bool:
    if not hasattr(leaf, "ndim") or leaf.ndim < 2:
        return False
    if leaf.size < _MIN_QUANT_ELEMS:
        return False
    name = "/".join(str(getattr(p, "key", p)) for p in path).lower()
    # Embedding tables feed gathers (dequant cannot fuse into a matmul)
    # and positional tables are tiny relative to impact — skip both.
    return "embed" not in name


def is_quantized(params: Any) -> bool:
    """True when the tree already carries QTensor leaves."""
    found = False

    def visit(leaf):
        nonlocal found
        if isinstance(leaf, QTensor):
            found = True
        return leaf

    jax.tree_util.tree_map(
        visit, params, is_leaf=lambda x: isinstance(x, QTensor)
    )
    return found


def quantize_tree(params: Any) -> Any:
    """Original params -> tree with matmul kernels as QTensor leaves.
    Idempotent: existing QTensor leaves pass through untouched (without
    the is_leaf stop, tree_map would descend into them and re-quantize
    the int8 q arrays)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            leaf if isinstance(leaf, QTensor)
            else _quantize_leaf(leaf) if _wants_quant(path, leaf)
            else leaf
        ),
        params,
        is_leaf=lambda x: isinstance(x, QTensor),
    )


def dequantize_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """Quantized tree -> standard tree (call INSIDE jit: XLA fuses each
    convert+scale into its consuming matmul instead of materializing)."""
    return jax.tree_util.tree_map(
        lambda leaf: (
            leaf.dequantize(dtype) if isinstance(leaf, QTensor) else leaf
        ),
        params,
        is_leaf=lambda x: isinstance(x, QTensor),
    )


def tree_weight_bytes(params: Any) -> int:
    """HBM bytes a (possibly quantized) params tree keeps resident."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if hasattr(leaf, "size"):
            total += leaf.size * leaf.dtype.itemsize
    return total


def quantized_weight_bytes(params: Any) -> int:
    """What :func:`quantize_tree` WOULD leave resident, computed without
    materializing the quantized tree (planner-side budgeting)."""
    total = 0

    def visit(path, leaf):
        nonlocal total
        if not hasattr(leaf, "size"):
            return leaf
        if _wants_quant(path, leaf):
            channels = leaf.shape[-1]
            total += leaf.size * 1 + channels * 4  # int8 q + f32 scales
        else:
            total += leaf.size * leaf.dtype.itemsize
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    return total
