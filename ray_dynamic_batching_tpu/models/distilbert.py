"""DistilBERT-style text classifier (flax linen, bf16) — the CPU smoke config.

BASELINE.json config 1 ("DistilBERT SST-2 classifier, single replica"): a
6-layer encoder with learned positions and a 2-way classification head. Serves
as the minimum end-to-end slice (SURVEY.md section 7 stage 2). Sequence inputs
are bucket-padded by the engine; the attention mask keeps padding inert.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)
from ray_dynamic_batching_tpu.ops import attention as attn_ops


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        D = x.shape[-1]
        H = D // self.num_heads
        qkv = nn.DenseGeneral(
            (3, self.num_heads, H),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="qkv",
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attn_ops.dot_product_attention(q, k, v, mask=mask)
        o = nn.DenseGeneral(
            D, axis=(-2, -1), dtype=self.dtype, param_dtype=jnp.float32, name="proj"
        )(o)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x + o).astype(self.dtype)
        y = nn.Dense(
            self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32, name="mlp_in"
        )(x)
        y = nn.gelu(y)
        y = nn.Dense(D, dtype=self.dtype, param_dtype=jnp.float32, name="mlp_out")(y)
        return nn.LayerNorm(dtype=jnp.float32, name="ln2")(x + y).astype(self.dtype)


class DistilBertModule(nn.Module):
    vocab_size: int = 30522
    max_len: int = 512
    hidden_dim: int = 768
    num_layers: int = 6
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 2
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, token_ids: jax.Array, attn_mask: jax.Array) -> jax.Array:
        B, T = token_ids.shape
        tok = nn.Embed(
            self.vocab_size,
            self.hidden_dim,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="tok_embed",
        )(token_ids)
        pos = nn.Embed(
            self.max_len,
            self.hidden_dim,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="pos_embed",
        )(jnp.arange(T)[None, :])
        x = nn.LayerNorm(dtype=jnp.float32, name="embed_ln")(tok + pos).astype(
            self.dtype
        )
        # [B, 1, Tq, Tk] — keys at padding positions are masked out.
        mask = attn_mask[:, None, None, :].astype(bool)
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                name=f"layer{i}",
            )(x, mask)
        cls = x[:, 0]
        h = nn.Dense(
            self.hidden_dim, dtype=self.dtype, param_dtype=jnp.float32, name="pre_head"
        )(cls)
        h = nn.relu(h)
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="head"
        )(h)


class DistilBert(ServableModel):
    family = "text_classifier"

    def __init__(
        self,
        dtype: jnp.dtype = jnp.bfloat16,
        name: str = "distilbert_sst2",
        **module_kwargs: Any,
    ):
        super().__init__(dtype)
        self.name = name
        self.module = DistilBertModule(dtype=dtype, **module_kwargs)

    def init(self, rng: jax.Array):
        return self.module.init(rng, *self.example_inputs(1, 16))

    def apply(self, params, token_ids: jax.Array, attn_mask: jax.Array) -> jax.Array:
        return self.module.apply(params, token_ids, attn_mask)

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        T = seq_len or 128
        return (
            jnp.zeros((batch_size, T), dtype=jnp.int32),
            jnp.ones((batch_size, T), dtype=jnp.int32),
        )

    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        T = seq_len or 128
        d, m = self.module.hidden_dim, self.module.mlp_dim
        per_layer = 4 * T * d * d + 2 * T * T * d + 2 * T * d * m
        return 2.0 * self.module.num_layers * per_layer

    def sharding_rules(self):
        # DenseGeneral((3, N, H)) kernel is [D, 3, N, H]: shard the heads axis.
        return [
            (r"qkv/kernel", P(None, None, "tp", None)),
            (r"proj/kernel", P("tp", None, None)),
            (r"mlp_in/kernel", P(None, "tp")),
            (r"mlp_out/kernel", P("tp", None)),
            (r"tok_embed/embedding", P(None, "tp")),
        ]


@register_model("distilbert_sst2", slo=ModelSLO(latency_slo_ms=100.0))
def _distilbert(**kwargs) -> DistilBert:
    return DistilBert(**kwargs)


@register_model("distilbert_tiny")
def _distilbert_tiny(**kwargs) -> DistilBert:
    return DistilBert(
        name="distilbert_tiny",
        vocab_size=1000,
        max_len=128,
        hidden_dim=64,
        num_layers=2,
        num_heads=4,
        mlp_dim=128,
        **kwargs,
    )
