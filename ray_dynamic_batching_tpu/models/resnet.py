"""ResNet for TPU inference (flax linen, bf16, NHWC).

Re-creates the capability of the reference's ``resnet50`` registry entry
(``293-project/src/scheduler.py:40-44`` loads torchvision resnet50 onto
``cuda:0``). Built TPU-first: NHWC layout (XLA's preferred conv layout on TPU),
bfloat16 compute with float32 BN statistics, inference-mode BN folded into
running averages, and a purely functional apply so every batch bucket compiles
to one fused XLA program on the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)


class BottleneckBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        needs_proj = x.shape[-1] != self.features * 4 or self.strides != 1
        residual = x
        norm = partial(
            nn.BatchNorm,
            use_running_average=True,
            momentum=0.9,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(
            self.features, (3, 3), strides=(self.strides, self.strides), name="conv2"
        )(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3")(y)
        if needs_proj:
            residual = conv(
                self.features * 4,
                (1, 1),
                strides=(self.strides, self.strides),
                name="proj_conv",
            )(x)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNetModule(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width,
            (7, 7),
            strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="stem_conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=True,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="stem_bn",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    features=self.width * (2**i),
                    strides=strides,
                    dtype=self.dtype,
                    name=f"stage{i}_block{j}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32, name="head"
        )(x)
        return x.astype(jnp.float32)


class ResNet(ServableModel):
    family = "vision"

    def __init__(
        self,
        stage_sizes: Sequence[int] = (3, 4, 6, 3),
        num_classes: int = 1000,
        image_size: int = 224,
        width: int = 64,
        dtype: jnp.dtype = jnp.bfloat16,
        name: str = "resnet50",
    ):
        super().__init__(dtype)
        self.name = name
        self.image_size = image_size
        self.module = ResNetModule(
            stage_sizes=stage_sizes,
            num_classes=num_classes,
            width=width,
            dtype=dtype,
        )

    def init(self, rng: jax.Array):
        x = self.example_inputs(1)[0]
        return self.module.init(rng, x)

    def apply(self, params, x: jax.Array) -> jax.Array:
        return self.module.apply(params, x)

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        return (
            jnp.zeros(
                (batch_size, self.image_size, self.image_size, 3), dtype=self.dtype
            ),
        )

    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        return 4.1e9 * 2  # ~4.1 GMACs for ResNet-50 @ 224


@register_model("resnet50", slo=ModelSLO(latency_slo_ms=2000.0))
def _resnet50(**kwargs) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), name="resnet50", **kwargs)


@register_model("resnet18_tiny")
def _resnet_tiny(**kwargs) -> ResNet:
    """Small config for CPU tests (stride-identical topology, 1/8 width)."""
    kwargs.setdefault("image_size", 32)
    kwargs.setdefault("width", 8)
    kwargs.setdefault("num_classes", 10)
    return ResNet(stage_sizes=(1, 1, 1, 1), name="resnet18_tiny", **kwargs)
