"""Servable-model interface + registry.

TPU-native replacement for the reference's in-process model registry
(``293-project/src/scheduler.py:40-44`` — dict name→torchvision constructor) and
its per-model SLO config (``scheduler.py:30-35``). Instead of eager torch
modules, a servable model here is a *pure apply function* plus enough metadata
for the profiler, the bucketing layer, and the mesh planner:

- ``init`` / ``apply``: functional params + jittable forward (XLA traces once
  per input shape bucket; no data-dependent Python control flow inside).
- ``example_inputs``: canonical input pytree per (batch, seq) bucket — the
  contract the profiler sweeps and the engine pads to.
- ``sharding_rules``: regex → ``PartitionSpec`` over logical mesh axes
  ("dp", "tp", ...) so the same model runs single-chip or pjit-sharded.
"""

from __future__ import annotations

import abc
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Any  # pytree


@dataclass(frozen=True)
class ModelSLO:
    """Per-model serving contract (ref: models_config, scheduler.py:30-35)."""

    latency_slo_ms: float
    # Optional per-model rate hint used by tests/load generators.
    expected_rate_rps: float = 0.0


class ServableModel(abc.ABC):
    """A model the engine can profile, bucket, schedule, and execute."""

    #: registry key, e.g. "resnet50"
    name: str = "unnamed"
    #: "vision" | "text_classifier" | "causal_lm" | "asr"
    family: str = "vision"

    def __init__(self, dtype: jnp.dtype = jnp.bfloat16):
        self.dtype = dtype

    # --- functional core -------------------------------------------------
    @abc.abstractmethod
    def init(self, rng: jax.Array) -> Params:
        """Initialize parameters (and any constant state, e.g. BN stats)."""

    @abc.abstractmethod
    def apply(self, params: Params, *inputs: jax.Array) -> Any:
        """Pure forward pass; must be jittable with static shapes."""

    # --- shape contract --------------------------------------------------
    @abc.abstractmethod
    def example_inputs(
        self, batch_size: int, seq_len: Optional[int] = None
    ) -> Tuple[jax.Array, ...]:
        """Canonical zero inputs for a (batch, seq) bucket."""

    def input_shapes(
        self, batch_size: int, seq_len: Optional[int] = None
    ) -> Tuple[jax.ShapeDtypeStruct, ...]:
        return tuple(
            jax.ShapeDtypeStruct(x.shape, x.dtype)
            for x in jax.eval_shape(lambda: self.example_inputs(batch_size, seq_len))
        )

    # --- planning metadata ----------------------------------------------
    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        """Rough forward FLOPs per sample (for roofline sanity checks)."""
        return 0.0

    def param_count(self, params: Params) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    def param_bytes(self, params: Params) -> int:
        return sum(
            int(x.size) * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params)
        )

    # --- distribution ----------------------------------------------------
    def sharding_rules(self) -> List[Tuple[str, P]]:
        """(param-path regex, PartitionSpec over logical axes) — first match wins.

        Logical axis names: "tp" (tensor-parallel), "dp" (data/replica),
        "sp" (sequence). Unmatched params replicate.
        """
        return []

    def partition_spec_for(self, path: str) -> P:
        for pattern, spec in self.sharding_rules():
            if re.search(pattern, path):
                return spec
        return P()


def param_path_specs(model: ServableModel, params: Params) -> Any:
    """Map every param leaf to its PartitionSpec via the model's rules."""

    from ray_dynamic_batching_tpu.utils.pytree import path_str

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    specs = [model.partition_spec_for(path_str(path)) for path, _leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


# --- registry (ref: model_registry, 293-project/src/scheduler.py:40-44) ---

_MODEL_REGISTRY: Dict[str, Callable[..., ServableModel]] = {}
_MODEL_SLOS: Dict[str, ModelSLO] = {}


def register_model(
    name: str, slo: Optional[ModelSLO] = None
) -> Callable[[Callable[..., ServableModel]], Callable[..., ServableModel]]:
    def deco(factory: Callable[..., ServableModel]) -> Callable[..., ServableModel]:
        _MODEL_REGISTRY[name] = factory
        if slo is not None:
            _MODEL_SLOS[name] = slo
        return factory

    return deco


def get_model(name: str, **kwargs: Any) -> ServableModel:
    if name not in _MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; registered: {sorted(_MODEL_REGISTRY)}"
        )
    return _MODEL_REGISTRY[name](**kwargs)


def get_slo(name: str) -> Optional[ModelSLO]:
    return _MODEL_SLOS.get(name)


def registered_models() -> List[str]:
    return sorted(_MODEL_REGISTRY)
