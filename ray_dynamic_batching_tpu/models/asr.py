"""Whisper-style streaming ASR — encoder-decoder with ragged audio batching.

BASELINE.json config 5 ("Whisper-large-v3 streaming ASR, ragged
variable-length batching") — a capability absent from the reference (its
serving path is fixed-shape vision, SURVEY.md §5 long-context/ragged note).
TPU-first decisions:

- Audio lengths are RAGGED; shapes must be static for XLA. Mel inputs are
  padded to *duration buckets* (``bucket_frames``) so each bucket compiles
  once, and the encoder consumes a frame-validity mask — identical in spirit
  to the text path's (batch, seq) buckets (engine/collate.py).
- Encoder: two strided convs downsample mel frames 2x, then bidirectional
  transformer layers on the MXU (bf16, static shapes).
- Decoder: causal self-attention with the same explicit KV cache as the
  causal LMs (decoder.py) plus cross-attention over encoder states; cross
  K/V are computed once per utterance at prefill and reused every decode
  step (they depend only on encoder output).
- Streaming: :class:`StreamingASR` feeds fixed-size audio chunks through
  encode+decode as they arrive, carrying the transcript prefix forward —
  chunked inference with one compiled program per chunk bucket.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)
from ray_dynamic_batching_tpu.ops import attention as attn_ops


@dataclasses.dataclass(frozen=True)
class ASRConfig:
    vocab_size: int = 51866          # whisper-large-v3 vocab
    n_mels: int = 80
    d_model: int = 1280
    enc_layers: int = 32
    dec_layers: int = 32
    num_heads: int = 20
    mlp_dim: int = 5120
    max_audio_frames: int = 3000     # 30 s of 10 ms mel frames
    max_text_len: int = 448
    sot_token: int = 50258           # start-of-transcript
    eot_token: int = 50257           # end-of-transcript

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


def sinusoids(length: int, channels: int) -> jax.Array:
    """Fixed sinusoidal positions (whisper-style encoder embedding)."""
    log_timescale = math.log(10000.0) / (channels // 2 - 1)
    inv = jnp.exp(-log_timescale * jnp.arange(channels // 2))
    scaled = jnp.arange(length)[:, None] * inv[None, :]
    return jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)


class EncoderLayer(nn.Module):
    cfg: ASRConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, frame_mask: jax.Array) -> jax.Array:
        cfg = self.cfg
        dense = lambda feats, name, axis=-1: nn.DenseGeneral(  # noqa: E731
            feats, axis=axis, dtype=self.dtype, param_dtype=jnp.float32,
            name=name,
        )
        y = nn.LayerNorm(dtype=jnp.float32, name="attn_norm")(x).astype(self.dtype)
        q = dense((cfg.num_heads, cfg.head_dim), "q")(y)
        k = dense((cfg.num_heads, cfg.head_dim), "k")(y)
        v = dense((cfg.num_heads, cfg.head_dim), "v")(y)
        # bidirectional over valid frames only (ragged padding masked)
        attn = attn_ops.self_attention(q, k, v, frame_mask, causal=False)
        x = x + dense(cfg.d_model, "o", axis=(-2, -1))(attn)
        y = nn.LayerNorm(dtype=jnp.float32, name="mlp_norm")(x).astype(self.dtype)
        y = nn.gelu(dense(cfg.mlp_dim, "mlp_up")(y))
        x = x + dense(cfg.d_model, "mlp_down")(y)
        return x


class AudioEncoder(nn.Module):
    cfg: ASRConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(
        self, mel: jax.Array, frame_mask: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """mel [B, T, n_mels], frame_mask [B, T] -> (states [B, T//2, D],
        state_mask [B, T//2])."""
        cfg = self.cfg
        x = nn.Conv(
            cfg.d_model, kernel_size=(3,), padding=1, dtype=self.dtype,
            param_dtype=jnp.float32, name="conv1",
        )(mel.astype(self.dtype))
        x = nn.gelu(x)
        x = nn.Conv(
            cfg.d_model, kernel_size=(3,), strides=(2,), padding=1,
            dtype=self.dtype, param_dtype=jnp.float32, name="conv2",
        )(x)
        x = nn.gelu(x)
        T2 = x.shape[1]
        x = x + sinusoids(T2, cfg.d_model).astype(self.dtype)[None]
        state_mask = frame_mask[:, ::2][:, :T2]
        for i in range(cfg.enc_layers):
            x = EncoderLayer(cfg, dtype=self.dtype, name=f"layer{i}")(
                x, state_mask
            )
        x = nn.LayerNorm(dtype=jnp.float32, name="final_norm")(x)
        return x.astype(self.dtype), state_mask


class CrossDecoderLayer(nn.Module):
    """Causal self-attention (+KV cache) then cross-attention over encoder
    states, as in whisper's text decoder."""

    cfg: ASRConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(
        self,
        x: jax.Array,                # [B, T, D]
        self_mask: jax.Array,        # [B, 1, T, S]
        enc_states: jax.Array,       # [B, Te, D]
        enc_mask: jax.Array,         # [B, Te]
        positions: jax.Array,        # [B, T]
        layer_cache: Optional[Tuple[jax.Array, jax.Array]] = None,
    ) -> Tuple[jax.Array, Optional[Tuple[jax.Array, jax.Array]]]:
        cfg = self.cfg
        dense = lambda feats, name, axis=-1: nn.DenseGeneral(  # noqa: E731
            feats, axis=axis, dtype=self.dtype, param_dtype=jnp.float32,
            name=name,
        )
        # --- causal self-attention with explicit cache (decoder.py style) --
        y = nn.LayerNorm(dtype=jnp.float32, name="self_norm")(x).astype(self.dtype)
        q = dense((cfg.num_heads, cfg.head_dim), "self_q")(y)
        k = dense((cfg.num_heads, cfg.head_dim), "self_k")(y)
        v = dense((cfg.num_heads, cfg.head_dim), "self_v")(y)
        new_cache = None
        if layer_cache is not None:
            k_cache, v_cache = layer_cache
            B, T = positions.shape
            if T == 1:
                rows = jnp.arange(B)
                idx = positions[:, 0]
                k_cache = k_cache.at[rows, idx].set(k[:, 0], mode="drop")
                v_cache = v_cache.at[rows, idx].set(v[:, 0], mode="drop")
            else:
                k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, 0, 0, 0))
                v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, 0, 0, 0))
            attn = attn_ops.dot_product_attention(q, k_cache, v_cache,
                                                  mask=self_mask)
            new_cache = (k_cache, v_cache)
        else:
            attn = attn_ops.dot_product_attention(q, k, v, mask=self_mask)
        x = x + dense(cfg.d_model, "self_o", axis=(-2, -1))(attn)

        # --- cross-attention over encoder states ---------------------------
        y = nn.LayerNorm(dtype=jnp.float32, name="cross_norm")(x).astype(self.dtype)
        qc = dense((cfg.num_heads, cfg.head_dim), "cross_q")(y)
        kc = dense((cfg.num_heads, cfg.head_dim), "cross_k")(enc_states)
        vc = dense((cfg.num_heads, cfg.head_dim), "cross_v")(enc_states)
        cmask = enc_mask[:, None, None, :].astype(bool)
        cattn = attn_ops.dot_product_attention(qc, kc, vc, mask=cmask)
        x = x + dense(cfg.d_model, "cross_o", axis=(-2, -1))(cattn)

        y = nn.LayerNorm(dtype=jnp.float32, name="mlp_norm")(x).astype(self.dtype)
        y = nn.gelu(dense(cfg.mlp_dim, "mlp_up")(y))
        return x + dense(cfg.d_model, "mlp_down")(y), new_cache


class TextDecoder(nn.Module):
    cfg: ASRConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,           # [B, T]
        positions: jax.Array,        # [B, T]
        self_mask: jax.Array,        # [B, 1, T, S]
        enc_states: jax.Array,
        enc_mask: jax.Array,
        cache: Optional["ASRCache"] = None,
    ) -> Tuple[jax.Array, Optional["ASRCache"]]:
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=self.dtype,
            param_dtype=jnp.float32, name="tok_embed",
        )
        pos_embed = nn.Embed(
            cfg.max_text_len, cfg.d_model, dtype=self.dtype,
            param_dtype=jnp.float32, name="pos_embed",
        )
        x = embed(tokens) + pos_embed(positions)
        new_k, new_v = [], []
        for i in range(cfg.dec_layers):
            layer_cache = (
                (cache.k[i], cache.v[i]) if cache is not None else None
            )
            x, updated = CrossDecoderLayer(
                cfg, dtype=self.dtype, name=f"layer{i}"
            )(x, self_mask, enc_states, enc_mask, positions, layer_cache)
            if updated is not None:
                new_k.append(updated[0])
                new_v.append(updated[1])
        x = nn.LayerNorm(dtype=jnp.float32, name="final_norm")(x)
        logits = embed.attend(x.astype(jnp.float32))  # tied head (whisper)
        out_cache = None
        if cache is not None:
            out_cache = ASRCache(
                k=jnp.stack(new_k), v=jnp.stack(new_v), lengths=cache.lengths
            )
        return logits, out_cache


from flax.struct import dataclass as pytree_dataclass  # noqa: E402


@pytree_dataclass
class ASRCache:
    k: jax.Array        # [L, B, S, N, H]
    v: jax.Array
    lengths: jax.Array  # [B]

    @staticmethod
    def zeros(cfg: ASRConfig, batch_size: int, max_len: Optional[int] = None,
              dtype=jnp.bfloat16) -> "ASRCache":
        S = max_len or cfg.max_text_len
        shape = (cfg.dec_layers, batch_size, S, cfg.num_heads, cfg.head_dim)
        return ASRCache(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((batch_size,), jnp.int32),
        )

    @property
    def capacity(self) -> int:
        return self.k.shape[2]


# --- ragged audio bucketing -------------------------------------------------

AUDIO_BUCKETS = (200, 500, 1000, 1500, 3000)  # mel frames (2s..30s @10ms)


def bucket_frames(n_frames: int,
                  buckets: Tuple[int, ...] = AUDIO_BUCKETS) -> int:
    """Smallest bucket holding n_frames (ragged lengths -> static shapes;
    one XLA compile per bucket, like the text path's seq buckets)."""
    for b in buckets:
        if n_frames <= b:
            return b
    return buckets[-1]


def collate_audio(
    mels: List[np.ndarray], batch_bucket: int,
    buckets: Tuple[int, ...] = AUDIO_BUCKETS,
) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged [T_i, n_mels] list -> (mel [B, Tb, n_mels], mask [B, Tb]);
    Tb = the duration bucket of the longest clip, B = batch bucket."""
    if not mels:
        raise ValueError("empty batch")
    if len(mels) > batch_bucket:
        raise ValueError(
            f"{len(mels)} clips exceed batch bucket {batch_bucket}; "
            "silently dropping audio is never acceptable"
        )
    n_mels = mels[0].shape[1]
    Tb = bucket_frames(max(m.shape[0] for m in mels), buckets)
    mel = np.zeros((batch_bucket, Tb, n_mels), np.float32)
    mask = np.zeros((batch_bucket, Tb), np.int32)
    for i, m_i in enumerate(mels):
        t = min(m_i.shape[0], Tb)
        mel[i, :t] = m_i[:t]
        mask[i, :t] = 1
    return mel, mask


# --- servable model ---------------------------------------------------------

class ASRModel(ServableModel):
    family = "asr"

    def __init__(self, cfg: ASRConfig, name: str, dtype=jnp.bfloat16):
        super().__init__(dtype)
        self.name = name
        self.cfg = cfg
        self.encoder = AudioEncoder(cfg, dtype=dtype)
        self.decoder = TextDecoder(cfg, dtype=dtype)

    # --- ServableModel (apply = full enc+dec teacher-forced pass) ---------
    def init(self, rng: jax.Array) -> Dict[str, Any]:
        mel, mel_mask, tokens, text_mask = self.example_inputs(1, 16)
        r1, r2 = jax.random.split(rng)
        enc_params = self.encoder.init(r1, mel, mel_mask)
        enc_states, enc_mask = self.encoder.apply(enc_params, mel, mel_mask)
        positions = jnp.arange(tokens.shape[1])[None, :]
        self_mask = _causal_mask(text_mask)
        dec_params = self.decoder.init(
            r2, tokens, positions, self_mask, enc_states, enc_mask
        )
        return {"encoder": enc_params, "decoder": dec_params}

    def apply(self, params, mel, mel_mask, tokens, text_mask) -> jax.Array:
        """Teacher-forced logits [B, T_text, V] (profiling + loss path)."""
        enc_states, enc_mask = self.encoder.apply(
            params["encoder"], mel, mel_mask
        )
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1])[None, :], tokens.shape
        )
        logits, _ = self.decoder.apply(
            params["decoder"], tokens, positions, _causal_mask(text_mask),
            enc_states, enc_mask,
        )
        return logits

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        T_text = seq_len or 16
        T_audio = AUDIO_BUCKETS[0]
        return (
            jnp.zeros((batch_size, T_audio, self.cfg.n_mels), jnp.float32),
            jnp.ones((batch_size, T_audio), jnp.int32),
            jnp.zeros((batch_size, T_text), jnp.int32),
            jnp.ones((batch_size, T_text), jnp.int32),
        )

    # --- encode / decode (serving path) -----------------------------------
    def encode(self, params, mel, mel_mask):
        return self.encoder.apply(params["encoder"], mel, mel_mask)

    def prefill(self, params, tokens, text_mask, enc_states, enc_mask,
                cache: ASRCache):
        B, T = tokens.shape
        S = cache.capacity
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        lengths = text_mask.sum(axis=1).astype(jnp.int32)
        base = _causal_mask(text_mask)
        if S > T:
            pad = jnp.zeros((B, 1, T, S - T), bool)
            mask = jnp.concatenate([base, pad], axis=-1)
        else:
            mask = base
        logits, new_cache = self.decoder.apply(
            params["decoder"], tokens, positions, mask, enc_states, enc_mask,
            cache,
        )
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0]
        return last, new_cache.replace(lengths=lengths)

    def decode_step(self, params, tokens, enc_states, enc_mask,
                    cache: ASRCache, active: jax.Array):
        in_bounds = cache.lengths < cache.capacity
        active = jnp.logical_and(active, in_bounds)
        positions = cache.lengths[:, None]
        pos = jnp.arange(cache.capacity)[None, None, None, :]
        mask = pos <= cache.lengths[:, None, None, None]
        logits, new_cache = self.decoder.apply(
            params["decoder"], tokens, positions, mask, enc_states, enc_mask,
            cache,
        )
        new_lengths = cache.lengths + active.astype(jnp.int32)
        return logits[:, 0], new_cache.replace(lengths=new_lengths)

    def make_cache(self, batch_size: int, max_len: Optional[int] = None):
        return ASRCache.zeros(self.cfg, batch_size, max_len, dtype=self.dtype)

    # --- planning ----------------------------------------------------------
    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        c = self.cfg
        Ta = (seq_len or AUDIO_BUCKETS[0]) // 2
        Tt = 32
        enc = c.enc_layers * Ta * (8 * c.d_model ** 2 + 4 * Ta * c.d_model)
        dec = c.dec_layers * Tt * (
            12 * c.d_model ** 2 + 4 * Tt * c.d_model + 4 * Ta * c.d_model
        )
        return float(enc + dec)

    def sharding_rules(self):
        return [
            (r"/(self_|cross_)?[qkv]/kernel", P(None, "tp", None)),
            (r"/(self_|cross_)?o/kernel", P("tp", None, None)),
            (r"mlp_up/kernel", P(None, "tp")),
            (r"mlp_down/kernel", P("tp", None)),
            (r"tok_embed/embedding", P("tp", None)),
        ]


def _causal_mask(token_mask: jax.Array) -> jax.Array:
    T = token_mask.shape[1]
    causal = jnp.tril(jnp.ones((T, T), bool))
    valid = token_mask[:, None, None, :].astype(bool)
    return causal[None, None, :, :] & valid


# --- streaming -------------------------------------------------------------

class StreamingASR:
    """Chunked streaming transcription: feed audio incrementally; each
    flush encodes the newest chunk bucket and greedily decodes, carrying
    the transcript prefix forward (whisper-style streaming at chunk
    granularity — one compiled program per (chunk bucket, text bucket))."""

    def __init__(self, model: ASRModel, params, chunk_frames: int = 200,
                 max_new_tokens: int = 32):
        self.model = model
        self.params = params
        self.chunk_frames = chunk_frames
        self.max_new_tokens = max_new_tokens
        self._buffer: List[np.ndarray] = []
        self._tokens: List[int] = [model.cfg.sot_token]
        self._encode = jax.jit(model.encode)
        self._prefill = jax.jit(model.prefill)
        self._step = jax.jit(model.decode_step)

    def reset(self) -> None:
        """Start a fresh stream, KEEPING the compiled programs (a serving
        replica reuses one StreamingASR across requests — re-instantiating
        would re-jit per request and recompile every bucket)."""
        self._buffer = []
        self._tokens = [self.model.cfg.sot_token]

    def feed(self, mel_frames: np.ndarray) -> Optional[List[int]]:
        """Append [T, n_mels] frames; when a full chunk accumulates,
        transcribe it and return the new token ids (else None)."""
        self._buffer.append(np.asarray(mel_frames, np.float32))
        total = sum(b.shape[0] for b in self._buffer)
        if total < self.chunk_frames:
            return None
        return self.flush()

    def flush(self) -> List[int]:
        """Transcribe everything buffered; returns newly emitted tokens."""
        if not self._buffer:
            return []
        audio = np.concatenate(self._buffer, axis=0)
        self._buffer = []
        mel, mask = collate_audio([audio], batch_bucket=1)
        enc_states, enc_mask = self._encode(self.params, mel, mask)
        cfg = self.model.cfg
        prefix = self._tokens[-cfg.max_text_len // 2:]
        new = self._greedy(enc_states, enc_mask, prefix)
        self._tokens.extend(new)
        return new

    def _greedy(self, enc_states, enc_mask, prefix: List[int]) -> List[int]:
        cfg = self.model.cfg
        T = 16
        while T < len(prefix):
            T *= 2
        tokens = np.zeros((1, T), np.int32)
        tokens[0, :len(prefix)] = prefix
        text_mask = np.zeros((1, T), np.int32)
        text_mask[0, :len(prefix)] = 1
        # cap at max_text_len: positions past the pos_embed table would
        # clamp-gather entry max_text_len-1 and silently corrupt output;
        # decode_step deactivates rows at cache capacity, so generation
        # stops cleanly at the model's limit instead
        cache = self.model.make_cache(
            1, max_len=min(T + self.max_new_tokens, cfg.max_text_len)
        )
        logits, cache = self._prefill(
            self.params, jnp.asarray(tokens), jnp.asarray(text_mask),
            enc_states, enc_mask, cache,
        )
        out: List[int] = []
        active = jnp.ones((1,), bool)
        for _ in range(self.max_new_tokens):
            nxt = int(jnp.argmax(logits[0]))
            if nxt == cfg.eot_token:
                break
            out.append(nxt)
            logits, cache = self._step(
                self.params, jnp.asarray([[nxt]], dtype=jnp.int32),
                enc_states, enc_mask, cache, active,
            )
        return out

    @property
    def transcript(self) -> List[int]:
        return list(self._tokens)


WHISPER_LARGE_V3 = ASRConfig()

WHISPER_TINY_TEST = ASRConfig(
    vocab_size=256,
    n_mels=16,
    d_model=64,
    enc_layers=2,
    dec_layers=2,
    num_heads=4,
    mlp_dim=128,
    max_audio_frames=400,
    max_text_len=64,
    sot_token=254,
    eot_token=255,
)


@register_model("whisper_large_v3", slo=ModelSLO(latency_slo_ms=4000.0))
def _whisper_large(**kwargs) -> ASRModel:
    return ASRModel(WHISPER_LARGE_V3, name="whisper_large_v3", **kwargs)


@register_model("whisper_tiny_test")
def _whisper_tiny(**kwargs) -> ASRModel:
    return ASRModel(WHISPER_TINY_TEST, name="whisper_tiny_test", **kwargs)
