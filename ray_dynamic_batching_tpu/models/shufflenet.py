"""ShuffleNet-v2 for TPU inference (flax linen, NHWC, bf16).

Capability parity with the reference's ``shufflenet_v2`` registry entry
(``293-project/src/scheduler.py:40-44``; profiled in
``293-project/profiling/shufflenet_20241123_104115_report.txt``). The channel
shuffle is a reshape/transpose pair, which XLA fuses into the surrounding
convs; depthwise convs use ``feature_group_count`` so they lower to TPU's
native grouped-conv path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)


def channel_shuffle(x: jax.Array, groups: int = 2) -> jax.Array:
    B, H, W, C = x.shape
    x = x.reshape(B, H, W, groups, C // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(B, H, W, C)


class ShuffleUnit(nn.Module):
    out_channels: int
    downsample: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        norm = partial(
            nn.BatchNorm,
            use_running_average=True,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        branch_c = self.out_channels // 2
        if self.downsample:
            # left branch: 3x3 dw stride 2 + 1x1
            left = conv(
                x.shape[-1],
                (3, 3),
                strides=(2, 2),
                feature_group_count=x.shape[-1],
                name="left_dw",
            )(x)
            left = norm(name="left_dw_bn")(left)
            left = conv(branch_c, (1, 1), name="left_pw")(left)
            left = nn.relu(norm(name="left_pw_bn")(left))
            right_in = x
        else:
            left, right_in = jnp.split(x, 2, axis=-1)
        stride = 2 if self.downsample else 1
        right = conv(branch_c, (1, 1), name="right_pw1")(right_in)
        right = nn.relu(norm(name="right_pw1_bn")(right))
        right = conv(
            branch_c,
            (3, 3),
            strides=(stride, stride),
            feature_group_count=branch_c,
            name="right_dw",
        )(right)
        right = norm(name="right_dw_bn")(right)
        right = conv(branch_c, (1, 1), name="right_pw2")(right)
        right = nn.relu(norm(name="right_pw2_bn")(right))
        return channel_shuffle(jnp.concatenate([left, right], axis=-1))


class ShuffleNetV2Module(nn.Module):
    stage_repeats: Sequence[int] = (4, 8, 4)
    stage_channels: Sequence[int] = (116, 232, 464)
    final_channels: int = 1024
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        x = nn.Conv(
            24,
            (3, 3),
            strides=(2, 2),
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="stem_conv",
        )(x)
        x = nn.relu(
            nn.BatchNorm(
                use_running_average=True,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="stem_bn",
            )(x)
        )
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for s, (repeats, channels) in enumerate(
            zip(self.stage_repeats, self.stage_channels)
        ):
            x = ShuffleUnit(
                channels, downsample=True, dtype=self.dtype, name=f"stage{s}_down"
            )(x)
            for i in range(repeats - 1):
                x = ShuffleUnit(
                    channels, dtype=self.dtype, name=f"stage{s}_unit{i}"
                )(x)
        x = nn.Conv(
            self.final_channels,
            (1, 1),
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="final_conv",
        )(x)
        x = nn.relu(
            nn.BatchNorm(
                use_running_average=True,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="final_bn",
            )(x)
        )
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32, name="head"
        )(x)
        return x.astype(jnp.float32)


class ShuffleNetV2(ServableModel):
    family = "vision"

    def __init__(
        self,
        image_size: int = 224,
        dtype: jnp.dtype = jnp.bfloat16,
        name: str = "shufflenet_v2",
        **module_kwargs: Any,
    ):
        super().__init__(dtype)
        self.name = name
        self.image_size = image_size
        self.module = ShuffleNetV2Module(dtype=dtype, **module_kwargs)

    def init(self, rng: jax.Array):
        return self.module.init(rng, self.example_inputs(1)[0])

    def apply(self, params, x: jax.Array) -> jax.Array:
        return self.module.apply(params, x)

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        return (
            jnp.zeros(
                (batch_size, self.image_size, self.image_size, 3), dtype=self.dtype
            ),
        )

    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        return 146e6 * 2  # ~146 MMACs for 1.0x @ 224


@register_model("shufflenet_v2", slo=ModelSLO(latency_slo_ms=1500.0))
def _shufflenet(**kwargs) -> ShuffleNetV2:
    return ShuffleNetV2(name="shufflenet_v2", **kwargs)


@register_model("shufflenet_tiny")
def _shufflenet_tiny(**kwargs) -> ShuffleNetV2:
    kwargs.setdefault("image_size", 32)
    return ShuffleNetV2(
        name="shufflenet_tiny",
        stage_repeats=(1, 1, 1),
        stage_channels=(16, 32, 64),
        final_channels=64,
        num_classes=10,
        **kwargs,
    )
