"""Import-all registry front door (ref: model_registry, scheduler.py:40-44)."""

from ray_dynamic_batching_tpu.models import (  # noqa: F401
    asr,
    causal_lm,
    distilbert,
    efficientnet,
    resnet,
    shufflenet,
    vit,
)
from ray_dynamic_batching_tpu.models.base import (  # noqa: F401
    ModelSLO,
    ServableModel,
    get_model,
    get_slo,
    param_path_specs,
    registered_models,
)
