"""Vision Transformer for TPU inference (flax linen, bf16).

Capability parity with the reference's ``vit_b_16`` / ViT-G profiling targets
(``293-project/src/scheduler.py:40-44``;
``293-project/profiling/vit_g16_20241123_154354_report.txt``). TPU-first
choices: attention through :mod:`ops.attention` (Pallas-fused on TPU), bf16
matmuls on the MXU with f32 layernorms, and TP sharding rules over the head
and MLP dimensions so big variants (ViT-G) shard with pjit instead of
time-slicing one chip.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)
from ray_dynamic_batching_tpu.ops import attention as attn_ops


class ViTBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        D = x.shape[-1]
        H = D // self.num_heads
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(self.dtype)
        qkv = nn.DenseGeneral(
            (3, self.num_heads, H),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="qkv",
        )(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attn_ops.dot_product_attention(q, k, v)
        o = nn.DenseGeneral(
            D,
            axis=(-2, -1),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="proj",
        )(o)
        x = x + o
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(self.dtype)
        y = nn.Dense(
            self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32, name="mlp_in"
        )(y)
        y = nn.gelu(y)
        y = nn.Dense(D, dtype=self.dtype, param_dtype=jnp.float32, name="mlp_out")(y)
        return x + y


class ViTModule(nn.Module):
    patch_size: int = 16
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B = x.shape[0]
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.hidden_dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(x)
        x = x.reshape(B, -1, self.hidden_dim)
        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, self.hidden_dim), jnp.float32
        )
        x = jnp.concatenate([jnp.tile(cls.astype(self.dtype), (B, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], self.hidden_dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        for i in range(self.num_layers):
            x = ViTBlock(
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="head"
        )(x[:, 0])


class ViT(ServableModel):
    family = "vision"

    def __init__(
        self,
        image_size: int = 224,
        dtype: jnp.dtype = jnp.bfloat16,
        name: str = "vit_b_16",
        **module_kwargs: Any,
    ):
        super().__init__(dtype)
        self.name = name
        self.image_size = image_size
        self.module = ViTModule(dtype=dtype, **module_kwargs)

    def init(self, rng: jax.Array):
        return self.module.init(rng, self.example_inputs(1)[0])

    def apply(self, params, x: jax.Array) -> jax.Array:
        return self.module.apply(params, x)

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        return (
            jnp.zeros(
                (batch_size, self.image_size, self.image_size, 3), dtype=self.dtype
            ),
        )

    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        n_tokens = (self.image_size // self.module.patch_size) ** 2 + 1
        d = self.module.hidden_dim
        per_layer = 4 * n_tokens * d * d + 2 * n_tokens * n_tokens * d
        per_layer += 2 * n_tokens * d * self.module.mlp_dim
        return 2.0 * self.module.num_layers * per_layer

    def sharding_rules(self):
        # Megatron-style: qkv/mlp_in column-split over heads, proj/mlp_out row-split.
        # DenseGeneral((3, N, H)) kernel is [D, 3, N, H]: shard the heads axis.
        return [
            (r"qkv/kernel", P(None, None, "tp", None)),
            (r"proj/kernel", P("tp", None, None)),
            (r"mlp_in/kernel", P(None, "tp")),
            (r"mlp_out/kernel", P("tp", None)),
        ]


@register_model("vit_b_16", slo=ModelSLO(latency_slo_ms=4000.0))
def _vit_b16(**kwargs) -> ViT:
    return ViT(name="vit_b_16", **kwargs)


@register_model("vit_g_14")
def _vit_g14(**kwargs) -> ViT:
    return ViT(
        name="vit_g_14",
        patch_size=14,
        hidden_dim=1664,
        num_layers=48,
        num_heads=16,
        mlp_dim=8192,
        **kwargs,
    )


@register_model("vit_tiny")
def _vit_tiny(**kwargs) -> ViT:
    kwargs.setdefault("image_size", 32)
    return ViT(
        name="vit_tiny",
        patch_size=8,
        hidden_dim=64,
        num_layers=2,
        num_heads=4,
        mlp_dim=128,
        num_classes=10,
        **kwargs,
    )
