"""EfficientNetV2-style CNN for TPU inference (flax linen, NHWC, bf16).

Capability parity with the reference's ``efficientnet`` registry entry
(``293-project/src/scheduler.py:40-44``; profiled in
``293-project/profiling/efficientnetv2_20241123_125206_report.txt``).
Implements the V2-S topology: fused-MBConv stages (3x3 conv replaces
expand+depthwise — better for the MXU) followed by MBConv stages with
squeeze-excite.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_dynamic_batching_tpu.models.base import (
    ModelSLO,
    ServableModel,
    register_model,
)

# (block_type, expand, channels, repeats, stride, use_se)
V2_S_STAGES: Tuple[Tuple[str, int, int, int, int, bool], ...] = (
    ("fused", 1, 24, 2, 1, False),
    ("fused", 4, 48, 4, 2, False),
    ("fused", 4, 64, 4, 2, False),
    ("mbconv", 4, 128, 6, 2, True),
    ("mbconv", 6, 160, 9, 1, True),
    ("mbconv", 6, 256, 15, 2, True),
)

TINY_STAGES: Tuple[Tuple[str, int, int, int, int, bool], ...] = (
    ("fused", 1, 16, 1, 1, False),
    ("fused", 2, 32, 1, 2, False),
    ("mbconv", 2, 64, 1, 2, True),
)


class SqueezeExcite(nn.Module):
    reduce_to: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        C = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Conv(self.reduce_to, (1, 1), dtype=self.dtype, param_dtype=jnp.float32, name="reduce")(s)
        s = nn.silu(s)
        s = nn.Conv(C, (1, 1), dtype=self.dtype, param_dtype=jnp.float32, name="expand")(s)
        return x * nn.sigmoid(s)


class MBConv(nn.Module):
    block_type: str  # "fused" | "mbconv"
    expand: int
    out_channels: int
    stride: int
    use_se: bool
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        norm = partial(
            nn.BatchNorm,
            use_running_average=True,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        in_c = x.shape[-1]
        mid = in_c * self.expand
        residual = x
        if self.block_type == "fused":
            y = conv(mid, (3, 3), strides=(self.stride, self.stride), name="fused_conv")(x)
            y = nn.silu(norm(name="fused_bn")(y))
            if self.expand != 1:
                y = conv(self.out_channels, (1, 1), name="project")(y)
                y = norm(name="project_bn")(y)
            else:
                y = conv(self.out_channels, (1, 1), name="project")(y) if self.out_channels != mid else y
        else:
            y = conv(mid, (1, 1), name="expand_conv")(x)
            y = nn.silu(norm(name="expand_bn")(y))
            y = conv(
                mid,
                (3, 3),
                strides=(self.stride, self.stride),
                feature_group_count=mid,
                name="dw_conv",
            )(y)
            y = nn.silu(norm(name="dw_bn")(y))
            if self.use_se:
                y = SqueezeExcite(max(1, in_c // 4), dtype=self.dtype, name="se")(y)
            y = conv(self.out_channels, (1, 1), name="project")(y)
            y = norm(name="project_bn")(y)
        if self.stride == 1 and in_c == self.out_channels:
            y = y + residual
        return y


class EfficientNetV2Module(nn.Module):
    stages: Tuple[Tuple[str, int, int, int, int, bool], ...] = V2_S_STAGES
    stem_channels: int = 24
    final_channels: int = 1280
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.stem_channels,
            (3, 3),
            strides=(2, 2),
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="stem_conv",
        )(x)
        x = nn.silu(
            nn.BatchNorm(
                use_running_average=True,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="stem_bn",
            )(x)
        )
        for s, (btype, expand, channels, repeats, stride, use_se) in enumerate(
            self.stages
        ):
            for i in range(repeats):
                x = MBConv(
                    block_type=btype,
                    expand=expand,
                    out_channels=channels,
                    stride=stride if i == 0 else 1,
                    use_se=use_se,
                    dtype=self.dtype,
                    name=f"stage{s}_block{i}",
                )(x)
        x = nn.Conv(
            self.final_channels,
            (1, 1),
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="final_conv",
        )(x)
        x = nn.silu(
            nn.BatchNorm(
                use_running_average=True,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="final_bn",
            )(x)
        )
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="head"
        )(x)


class EfficientNetV2(ServableModel):
    family = "vision"

    def __init__(
        self,
        image_size: int = 384,
        dtype: jnp.dtype = jnp.bfloat16,
        name: str = "efficientnet_v2s",
        **module_kwargs: Any,
    ):
        super().__init__(dtype)
        self.name = name
        self.image_size = image_size
        self.module = EfficientNetV2Module(dtype=dtype, **module_kwargs)

    def init(self, rng: jax.Array):
        return self.module.init(rng, self.example_inputs(1)[0])

    def apply(self, params, x: jax.Array) -> jax.Array:
        return self.module.apply(params, x)

    def example_inputs(self, batch_size: int, seq_len: Optional[int] = None):
        return (
            jnp.zeros(
                (batch_size, self.image_size, self.image_size, 3), dtype=self.dtype
            ),
        )

    def flops_per_sample(self, seq_len: Optional[int] = None) -> float:
        return 8.8e9 * 2  # ~8.8 GMACs for V2-S @ 384


@register_model("efficientnet_v2s", slo=ModelSLO(latency_slo_ms=40.0))
def _efficientnet(**kwargs) -> EfficientNetV2:
    return EfficientNetV2(**kwargs)


@register_model("efficientnet_tiny")
def _efficientnet_tiny(**kwargs) -> EfficientNetV2:
    kwargs.setdefault("image_size", 32)
    return EfficientNetV2(
        name="efficientnet_tiny",
        stages=TINY_STAGES,
        stem_channels=8,
        final_channels=64,
        num_classes=10,
        **kwargs,
    )
