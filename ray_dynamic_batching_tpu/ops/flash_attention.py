"""Pallas TPU flash attention — the framework's hot prefill kernel.

The reference serves its models through eager torch forwards
(``293-project/src/scheduler.py:435-452``); its attention FLOPs live inside
torchvision/HF modules. On TPU the prefill attention is the one op worth a
hand kernel: a fused tiled online-softmax keeps the [Tq, Tk] score matrix out
of HBM entirely (it never materializes), so the op stays MXU-bound instead of
HBM-bound. Decode steps (Tq == 1) stay on the XLA path — they are
bandwidth-bound KV scans where a custom kernel buys nothing.

Design (FlashAttention-2 style, one pass over KV):
- grid (B, N, ceil(Tq/block_q)); each program owns one query tile of one head.
- K/V for the head are resident in VMEM (seq buckets cap Tk, so at 8k seq,
  bf16, H=128 the pair costs 4 MB — comfortably under the ~16 MB budget).
- inner ``fori_loop`` over KV tiles carries (m, l, acc) in registers/VMEM:
  m/l rescaling per tile, scores and accumulator in f32 (bf16 inputs go
  through the MXU with f32 accumulation via ``preferred_element_type``).
- causal masking is computed from iota (no mask tensor traffic); an explicit
  mask (padding / decode windows) streams per-tile as int8.
- GQA: query head n reads kv head n // (N // K) via the BlockSpec index map —
  no ``jnp.repeat`` materialization (the XLA fallback pays that copy).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_dynamic_batching_tpu.ops import tile_math

NEG_INF = -1e30

# Query tiles below this aren't worth a kernel launch (decode steps).
MIN_QUERY_FOR_PALLAS = 16


def _attn_kernel(
    q_ref,      # [1, 1, block_q, H]   (B N T H layout: T, H are the tiled dims)
    k_ref,      # [1, 1, Tk, H]
    v_ref,      # [1, 1, Tk, H]
    mask_ref,   # [1, block_q, Tk] int8, or None
    o_ref,      # [1, 1, block_q, H]
    *,
    scale: float,
    causal: bool,
    block_k: int,
    q_len: int,
    kv_len: int,
):
    iq = pl.program_id(2)
    block_q = q_ref.shape[2]
    H = q_ref.shape[3]
    Tk = k_ref.shape[2]
    num_kb = pl.cdiv(Tk, block_k)

    # Keep matmul operands in input dtype (bf16 runs the MXU at full rate;
    # f32 would quarter it) and accumulate in f32 via preferred_element_type.
    q = q_ref[0, 0, :, :]  # [block_q, H]
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    if causal:
        # Query row r may attend keys <= r + (kv_len - q_len); KV tiles fully
        # beyond the last valid diagonal contribute nothing — stop early.
        last_key = (iq + 1) * block_q - 1 + (kv_len - q_len)
        kb_hi = jnp.minimum(num_kb, pl.cdiv(last_key + 1, block_k))
    else:
        kb_hi = num_kb

    def body(jk, carry):
        m_prev, l_prev, acc_prev = carry
        k_tile = k_ref[0, 0, pl.ds(jk * block_k, block_k), :]  # [block_k, H]
        v_tile = v_ref[0, 0, pl.ds(jk * block_k, block_k), :]
        s = jax.lax.dot_general(
            q,
            k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k] f32

        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < kv_len  # tail tile past Tk
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos + (kv_len - q_len))
        if mask_ref is not None:
            # The streamed mask folds in ARITHMETICALLY (f32 multiply-add),
            # not via boolean ops: an i1 vector derived from a VMEM-streamed
            # tile trips a Mosaic relayout bug ("non-singleton logical
            # dimension is replicated in destination but not in source") on
            # v5 hardware; iota-derived booleans are fine.
            m_tile = mask_ref[0, :, pl.ds(jk * block_k, block_k)]
            mf = m_tile.astype(jnp.float32)                  # 1 keep, 0 drop
            s = s + (mf - 1.0) * (-NEG_INF)
        s = jnp.where(valid, s, NEG_INF)

        m_cur = jnp.max(s, axis=-1, keepdims=True)          # [block_q, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        # A fully-masked row has s == m_new == NEG_INF, where exp(s - m_new)
        # would be 1 — zero those probs explicitly via the validity mask
        # (and the f32 mask for rows masked only by mask_ref).
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)        # [block_q, block_k]
        if mask_ref is not None:
            p = p * mf
        corr = jnp.exp(m_prev - m_new)                       # [block_q, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_tile.dtype),
            v_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, H] f32
        acc_new = acc_prev * corr + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, H), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, kb_hi, body, (m0, l0, acc0))

    # Fully-masked rows (padding) have l == 0 — emit 0, not NaN.
    out = acc / jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


def _pick_block(t: int, target: int) -> int:
    """Largest divisor of ``t`` at most ``target``, preferring
    sublane-aligned (8-multiple) divisors: a non-dividing block's ds()
    would clamp its start like dynamic_slice and silently re-read
    shifted rows that the validity iota then mislabels, so blocks must
    divide — and unaligned tiles both waste sublanes and trip Mosaic's
    bf16 mixed-type broadcast bug."""
    if t <= target:
        return t
    for cand in range(target, 0, -1):
        if t % cand == 0 and cand % 8 == 0:
            return cand
    for cand in range(target, 0, -1):
        if t % cand == 0:
            return cand
    return t


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def _flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array],
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    B, Tq, N, H = q.shape
    _, Tk, K, _ = k.shape
    group = N // K
    grid = (B, N, pl.cdiv(Tq, block_q))

    # B N T H layout so the tiled dims (T, H) are the trailing two — the TPU
    # lowering requires (8, 128)-aligned trailing block dims. XLA fuses these
    # transposes into the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    in_specs = [
        pl.BlockSpec(
            (1, 1, block_q, H), lambda b, n, i: (b, n, i, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, Tk, H), lambda b, n, i: (b, n // group, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, Tk, H), lambda b, n, i: (b, n // group, 0, 0),
            memory_space=pltpu.VMEM,
        ),
    ]
    args = [qt, kt, vt]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec(
                (1, block_q, Tk), lambda b, n, i: (b, i, 0),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(mask)
    else:
        in_specs.append(None)
        args.append(None)

    kernel = functools.partial(
        _attn_kernel,
        scale=scale,
        causal=causal,
        block_k=block_k,
        q_len=Tq,
        kv_len=Tk,
    )
    if mask is None:
        def kernel_nomask(q_ref, k_ref, v_ref, o_ref):
            return kernel(q_ref, k_ref, v_ref, None, o_ref)

        call_kernel = kernel_nomask
        in_specs = in_specs[:3]
        args = args[:3]
    else:
        call_kernel = kernel

    flops = 4 * B * N * Tq * Tk * H  # qk^T + pv
    bytes_accessed = (
        q.size * q.dtype.itemsize
        + k.size * k.dtype.itemsize
        + v.size * v.dtype.itemsize
        + q.size * q.dtype.itemsize
    )
    out = pl.pallas_call(
        call_kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, H), lambda b, n, i: (b, n, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops, bytes_accessed=bytes_accessed, transcendentals=B * N * Tq * Tk
        ),
        interpret=interpret,
    )(*args)
    return out.transpose(0, 2, 1, 3)  # back to [B, Tq, N, H]


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> Optional[jax.Array]:
    """Fused attention; returns None when the shape isn't worth a kernel
    (tiny decode queries, GQA head counts that don't divide) so the
    dispatcher (:mod:`ray_dynamic_batching_tpu.ops.attention`) falls back to XLA.

    Shapes: q [B, Tq, N, H], k/v [B, Tk, K, H], mask broadcastable to
    [B, 1, Tq, Tk] (True = attend).
    """
    B, Tq, N, H = q.shape
    _, Tk, K, _ = k.shape
    if Tq < MIN_QUERY_FOR_PALLAS:
        return None
    if K == 0 or N % K != 0:
        return None
    scale = scale if scale is not None else H ** -0.5
    block_q = _pick_block(Tq, block_q)
    block_k = _pick_block(Tk, block_k)
    # Sub-32-bit inputs with a sublane-unaligned query tile trip a
    # Mosaic verifier bug (bf16 [197, H] dot under preferred f32 emits a
    # mixed-type vector.broadcast — ViT's CLS+14x14=197 sequence found
    # it); f32 lowers fine at any alignment, so only narrow shapes
    # decline to XLA (pinned in tests/test_tpu_lowering.py).
    if q.dtype.itemsize < 4 and block_q % 8 != 0:
        return None
    # Degenerate tiling (prime-ish sequence lengths -> width-<8 tiles at
    # <=1/128 MXU utilization, e.g. ViT-G/14's 257) is not worth a
    # kernel: XLA's fused attention handles these shapes well.
    if block_q < 8 or block_k < 8:
        return None
    # Per-grid-step VMEM guard sharing the runtime/static footprint model
    # (ops/tile_math.py): the resident K/V pair, the q/out tiles, and the
    # streamed int8 mask tile, all padded and double-buffered, must fit
    # the block budget — the docstring's "K/V comfortably resident"
    # assumption, now enforced instead of assumed. Over-budget shapes
    # (e.g. masked multi-k seq where the [block_q, Tk] mask tile alone
    # costs Tq*Tk bytes) decline to XLA like every other fallback.
    blocks = (
        2 * tile_math.padded_block_bytes((1, 1, Tk, H), k.dtype.itemsize)
        + 2 * tile_math.padded_block_bytes((1, 1, block_q, H),
                                           q.dtype.itemsize)
    )
    if mask is not None:
        blocks += tile_math.padded_block_bytes((1, block_q, Tk), 1)
    if tile_math.DOUBLE_BUFFER * blocks > tile_math.VMEM_BLOCK_BUDGET_BYTES:
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    mask_i8 = None
    if mask is not None:
        # [B, 1, Tq, Tk] (or broadcastable) -> dense [B, Tq, Tk] int8 tiles.
        m4 = jnp.broadcast_to(mask, (B, 1, Tq, Tk)) if mask.ndim == 4 else mask
        mask_i8 = jnp.broadcast_to(
            m4.reshape(B, Tq, Tk) if m4.ndim == 4 else m4, (B, Tq, Tk)
        ).astype(jnp.int8)
    return _flash_attention(
        q, k, v, mask_i8,
        causal=causal, scale=float(scale),
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
