"""Pallas TPU flash attention (filled in by ops task; returns None to fall back).

Placeholder module so the dispatcher import is stable; the fused kernel lands
with the Pallas ops milestone.
"""

from __future__ import annotations

from typing import Optional

import jax


def flash_attention(q, k, v, *, causal=False, mask=None, scale=None) -> Optional[jax.Array]:
    return None  # fall back to XLA reference until the kernel lands
