"""Pallas TPU decode attention — the KV-scan kernel for small query
windows (plain decode Tq == 1, speculative-verify Tq == k+1, small
prefill buckets).

Decode is HBM-bandwidth-bound: every substep reads the full KV capacity
(static shapes — see ``serve/llm.py``'s capacity-bucket rationale) to
produce one token per slot. The XLA fallback pays two avoidable HBM
costs on that scan (``ops/attention.py::_xla_attention``):

- **GQA materialization**: ``jnp.repeat`` expands K/V to the full query
  head count before the einsum — N/K fresh copies of the cache read
  land in HBM every substep (llama-3 geometry: 4x).
- **Logit round-trip**: the [B, N, Tq, S] f32 logits + softmax
  intermediates materialize between two einsums instead of living in
  VMEM.

This kernel fuses the scan FlashAttention-style: grid (B, K // kb);
each program owns one slot's block of ``kb`` KV heads, reads each
[S, H] K/V slab exactly once (all Tq window rows and all G = N/K query
heads sharing a KV head ride the same read), runs the online softmax
over KV tiles in VMEM, and writes the [kb, Tq*G, H] output — GQA via
layout, no repeat. Heads are blocked because the TPU lowering requires
the trailing two block dims to be (8, 128)-tile-aligned or span the
array: K/V live as [B, S, K, H], so a one-head block (trailing dims
(1, H)) is illegal — ``kb`` is 8 when K divides into 8-groups, else the
full K (span). A layout transpose instead would materialize a full
KV-cache copy every substep, which is the exact HBM cost this kernel
exists to avoid.
Large prefill tiles stay on the flash kernel
(``ops/flash_attention.py``); this covers the decode half VERDICT r4 #8
called out (the reference has no decode engine to compare against — its
serving path is fixed-shape vision forwards,
``293-project/src/scheduler.py:435-452``).

Masking: windows arrive as a [B, 1, Tq, S] boolean (True = attend —
``models/decoder.py::decode_mask`` for Tq == 1, ``verify_step``'s
per-row scatter windows for the speculative path), streamed as int8
[Tq, S] per row — Tq bytes per KV position vs the 2H-byte K/V read they
gate.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

# Windows past this ride the flash kernel (>= 16) or XLA (9..15): the
# whole-KV-resident scan layout is sized for decode-shaped reads, not
# prefill tiles.
MAX_WINDOW_FOR_KERNEL = 8


def _decode_kernel(
    q_ref,      # [1, kb, Tq*G, H]   rows ordered (t, g)
    k_ref,      # [1, S, kb, H]
    v_ref,      # [1, S, kb, H]
    mask_ref,   # [1, Tq, S] int8, or None
    o_ref,      # [1, kb, Tq*G, H]
    *,
    scale: float,
    block_k: int,
    kv_len: int,
    window: int,
):
    kb = q_ref.shape[1]
    R = q_ref.shape[2]          # Tq * G
    H = q_ref.shape[3]
    G = R // window
    num_kb = pl.cdiv(kv_len, block_k)

    for h in range(kb):         # static unroll: this program's KV heads
        q = q_ref[0, h, :, :]   # [R, H]

        def body(jk, carry):
            m_prev, l_prev, acc_prev = carry
            ds = pl.ds(jk * block_k, block_k)
            k_tile = k_ref[0, ds, h, :]  # [block_k, H]
            v_tile = v_ref[0, ds, h, :]
            s = jax.lax.dot_general(
                q, k_tile,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [R, block_k] f32

            k_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (R, block_k), 1
            )
            valid = k_pos < kv_len  # tail tile past S
            if mask_ref is not None:
                mvals = mask_ref[0, :, ds] != 0
                # [Tq, block_k] -> one row per (t, g): g shares t's window.
                rows = jnp.broadcast_to(
                    mvals[:, None, :], (window, G, block_k)
                ).reshape(R, block_k)
                valid = jnp.logical_and(valid, rows)
            s = jnp.where(valid, s, NEG_INF)

            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))  # [R]
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur[:, None])  # [R, block_k]
            l_cur = l_prev * alpha + jnp.sum(p, axis=1)
            acc = acc_prev * alpha[:, None] + jax.lax.dot_general(
                p.astype(v_tile.dtype), v_tile,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [R, H]
            return m_cur, l_cur, acc

        m0 = jnp.full((R,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((R,), jnp.float32)
        acc0 = jnp.zeros((R, H), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
        # A fully-masked row (inactive spec rows are steered out of
        # bounds; their outputs are never consumed) -> zeros, not NaN.
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, h, :, :] = (acc / l[:, None]).astype(o_ref.dtype)


def _pick_heads_block(K: int) -> int:
    """Largest-tile-legal KV-head block: trailing-two block dims on the
    [B, S, K, H] cache are (kb, H), so kb must be a multiple of 8 or span
    K exactly (the TPU lowering's divisible-by-(8,128)-or-equal rule)."""
    if K % 8 == 0 and K > 8:
        return 8
    return K


# Decline-to-XLA ceiling for this call's VMEM-resident blocks (~16 MB
# VMEM/core, double-buffered pipelining means blocks are live twice).
VMEM_BLOCK_BUDGET_BYTES = 6 * 1024 * 1024


def _block_bytes(S, K, H, R, window, kv_itemsize, q_itemsize,
                 with_mask) -> int:
    kb = _pick_heads_block(K)
    kv = 2 * S * kb * H * kv_itemsize
    qo = 2 * kb * R * H * q_itemsize
    mask = window * S if with_mask else 0
    return kv + qo + mask


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "window", "interpret")
)
def _decode_attention(
    q: jax.Array,      # [B, K, Tq*G, H]  rows ordered (t, g)
    k: jax.Array,      # [B, S, K, H]
    v: jax.Array,
    mask: Optional[jax.Array],  # [B, Tq, S] int8, or None
    *,
    scale: float,
    block_k: int,
    window: int,
    interpret: bool,
) -> jax.Array:
    B, K, R, H = q.shape
    S = k.shape[1]
    kb = _pick_heads_block(K)
    in_specs = [
        pl.BlockSpec((1, kb, R, H), lambda b, j: (b, j, 0, 0)),
        pl.BlockSpec((1, S, kb, H), lambda b, j: (b, 0, j, 0)),
        pl.BlockSpec((1, S, kb, H), lambda b, j: (b, 0, j, 0)),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, window, S), lambda b, j: (b, 0, 0)))
        args.append(mask)
        kernel = functools.partial(
            _decode_kernel, scale=scale, block_k=block_k, kv_len=S,
            window=window,
        )
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref):
            _decode_kernel(
                q_ref, k_ref, v_ref, None, o_ref,
                scale=scale, block_k=block_k, kv_len=S, window=window,
            )
    return pl.pallas_call(
        kernel,
        grid=(B, K // kb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kb, R, H), lambda b, j: (b, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K, R, H), q.dtype),
        interpret=interpret,
    )(*args)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> Optional[jax.Array]:
    """Fused small-window attention; returns None when the shapes aren't
    the decode pattern (caller falls back to flash/XLA, same contract as
    ``flash_attention.flash_attention``).

    q [B, Tq, N, H] with Tq <= MAX_WINDOW_FOR_KERNEL; k/v [B, S, K, H]
    with K dividing N; mask None or broadcastable to [B, 1, Tq, S]
    (True = attend). The KV-head grouping matches ``_xla_attention``'s
    ``jnp.repeat`` semantics: query head n reads kv head n // (N // K).
    """
    if q.ndim != 4 or k.ndim != 4:
        return None
    B, Tq, N, H = q.shape
    _, S, K, _ = k.shape
    if not (1 <= Tq <= MAX_WINDOW_FOR_KERNEL):
        return None
    if K == 0 or N % K != 0 or v.shape != k.shape:
        return None
    G = N // K
    if mask is not None:
        if mask.shape[-1] != S:
            return None
        try:
            mask = jnp.broadcast_to(
                mask, (B, 1, Tq, S)
            ).reshape(B, Tq, S).astype(jnp.int8)
        except (TypeError, ValueError):
            # e.g. a per-head [B, N, Tq, S] mask: not this kernel's
            # pattern — decline so the caller falls back to XLA, which
            # handles arbitrary masks.
            return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Whole-KV-resident layout: a geometry whose per-program blocks would
    # overflow VMEM (large capacity x wide heads, e.g. 8B at S >= 2k)
    # falls back to XLA rather than failing to lower on chip.
    if _block_bytes(
        S, K, H, Tq * G, Tq, k.dtype.itemsize, q.dtype.itemsize,
        mask is not None,
    ) > VMEM_BLOCK_BUDGET_BYTES:
        return None
    scale = scale if scale is not None else H ** -0.5
    # Block must DIVIDE the capacity (same rule as the flash kernel's
    # _pick_block): a ragged tail tile's ds() would CLAMP its start like
    # dynamic_slice, silently re-reading shifted rows that the validity
    # iota then mislabels.
    from ray_dynamic_batching_tpu.ops.flash_attention import _pick_block

    block_k = _pick_block(S, max(1, min(block_k, S)))
    # Rows ordered (t, g) per kv head: [B, Tq, K, G, H] -> [B, K, Tq*G, H].
    q_r = q.reshape(B, Tq, K, G, H).transpose(0, 2, 1, 3, 4).reshape(
        B, K, Tq * G, H
    )
    out = _decode_attention(
        q_r, k, v, mask,
        scale=float(scale), block_k=int(block_k), window=int(Tq),
        interpret=bool(interpret),
    )
    return out.reshape(B, K, Tq, G, H).transpose(0, 2, 1, 3, 4).reshape(
        B, Tq, N, H
    )
