"""Pallas TPU decode attention — the KV-scan kernel for Tq == 1 steps.

Decode is HBM-bandwidth-bound: every substep reads the full KV capacity
(static shapes — see ``serve/llm.py``'s capacity-bucket rationale) to
produce one token per slot. The XLA fallback pays two avoidable HBM
costs on that scan (``ops/attention.py::_xla_attention``):

- **GQA materialization**: ``jnp.repeat`` expands K/V to the full query
  head count before the einsum — N/K fresh copies of the cache read
  land in HBM every substep (llama-3 geometry: 4x).
- **Logit round-trip**: the [B, N, 1, S] f32 logits + softmax
  intermediates materialize between two einsums instead of living in
  VMEM.

This kernel fuses the scan FlashAttention-style: grid (B, K); each
program owns one slot's one KV head, reads its [S, H] K/V slab exactly
once, runs the online softmax over KV tiles in VMEM, and writes the
[G, H] output for the G = N/K query heads sharing that KV head — GQA
via layout, no repeat. Prefill stays on the flash kernel
(``ops/flash_attention.py``); this covers the decode half VERDICT r4 #8
called out (the reference has no decode engine to compare against — its
serving path is fixed-shape vision forwards,
``293-project/src/scheduler.py:435-452``).

Masking: decode windows arrive as a [B, 1, 1, S] boolean (True =
attend, ``models/decoder.py::decode_mask``), streamed as int8 [B, S] —
one byte per KV row vs the 2H-byte K/V read it gates.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _decode_kernel(
    q_ref,      # [1, 1, G, H]
    k_ref,      # [1, S, 1, H]
    v_ref,      # [1, S, 1, H]
    mask_ref,   # [1, S] int8, or None
    o_ref,      # [1, 1, G, H]
    *,
    scale: float,
    block_k: int,
    kv_len: int,
):
    G = q_ref.shape[2]
    H = q_ref.shape[3]
    q = q_ref[0, 0, :, :]  # [G, H]
    num_kb = pl.cdiv(kv_len, block_k)

    def body(jk, carry):
        m_prev, l_prev, acc_prev = carry
        k_tile = k_ref[0, pl.ds(jk * block_k, block_k), 0, :]  # [block_k, H]
        v_tile = v_ref[0, pl.ds(jk * block_k, block_k), 0, :]
        s = jax.lax.dot_general(
            q, k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, block_k] f32

        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (G, block_k), 1
        )
        valid = k_pos < kv_len  # tail tile past S
        if mask_ref is not None:
            mvals = mask_ref[0, pl.ds(jk * block_k, block_k)] != 0
            valid = jnp.logical_and(valid, mvals[None, :])
        s = jnp.where(valid, s, NEG_INF)

        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))  # [G]
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])  # [G, block_k]
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc_prev * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_tile.dtype), v_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, H]
        return m_cur, l_cur, acc

    m0 = jnp.full((G,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((G,), jnp.float32)
    acc0 = jnp.zeros((G, H), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    # A fully-masked row (lengths=0 never happens in the engine, but be
    # total): l == 0 -> emit zeros instead of NaN.
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0, :, :] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret")
)
def _decode_attention(
    q: jax.Array,      # [B, K, G, H]
    k: jax.Array,      # [B, S, K, H]
    v: jax.Array,
    mask: Optional[jax.Array],  # [B, S] int8, or None
    *,
    scale: float,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    B, K, G, H = q.shape
    S = k.shape[1]
    in_specs = [
        pl.BlockSpec((1, 1, G, H), lambda b, j: (b, j, 0, 0)),
        pl.BlockSpec((1, S, 1, H), lambda b, j: (b, 0, j, 0)),
        pl.BlockSpec((1, S, 1, H), lambda b, j: (b, 0, j, 0)),
    ]
    args = [q.reshape(B, K, G, H), k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, S), lambda b, j: (b, 0)))
        args.append(mask)
        kernel = functools.partial(
            _decode_kernel, scale=scale, block_k=block_k, kv_len=S,
        )
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref):
            _decode_kernel(
                q_ref, k_ref, v_ref, None, o_ref,
                scale=scale, block_k=block_k, kv_len=S,
            )
    return pl.pallas_call(
        kernel,
        grid=(B, K),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, H), lambda b, j: (b, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K, G, H), q.dtype),
        interpret=interpret,
    )(*args)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> Optional[jax.Array]:
    """Fused single-token attention; returns None when the shapes aren't
    the decode pattern (caller falls back to XLA, same contract as
    ``flash_attention.flash_attention``).

    q [B, 1, N, H]; k/v [B, S, K, H] with K dividing N; mask None or
    broadcastable to [B, 1, 1, S] (True = attend). The KV-head grouping
    matches ``_xla_attention``'s ``jnp.repeat`` semantics: query head n
    reads kv head n // (N // K).
    """
    if q.ndim != 4 or k.ndim != 4 or q.shape[1] != 1:
        return None
    B, _, N, H = q.shape
    _, S, K, _ = k.shape
    if K == 0 or N % K != 0 or v.shape != k.shape:
        return None
    if mask is not None:
        if mask.shape[-1] != S:
            return None
        try:
            mask = jnp.broadcast_to(
                mask.reshape(mask.shape[0], -1, S)[:, -1, :], (B, S)
            ).astype(jnp.int8)
        except TypeError:
            return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else H ** -0.5
    # Block must DIVIDE the capacity (same rule as the flash kernel's
    # _pick_block): a ragged tail tile's ds() would CLAMP its start like
    # dynamic_slice, silently re-reading shifted rows that the validity
    # iota then mislabels.
    from ray_dynamic_batching_tpu.ops.flash_attention import _pick_block

    block_k = _pick_block(S, max(1, min(block_k, S)))
    out = _decode_attention(
        q.reshape(B, K, N // K, H), k, v, mask,
        scale=float(scale), block_k=int(block_k), interpret=bool(interpret),
    )
    return out.reshape(B, 1, N, H)
