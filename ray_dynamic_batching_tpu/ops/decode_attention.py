"""Pallas TPU decode attention — the KV-scan kernel for small query
windows (plain decode Tq == 1, speculative-verify Tq == k+1, small
prefill buckets).

Decode is HBM-bandwidth-bound: every substep reads the full KV capacity
(static shapes — see ``serve/llm.py``'s capacity-bucket rationale) to
produce one token per slot. The XLA fallback pays two avoidable HBM
costs on that scan (``ops/attention.py::_xla_attention``):

- **GQA materialization**: ``jnp.repeat`` expands K/V to the full query
  head count before the einsum — N/K fresh copies of the cache read
  land in HBM every substep (llama-3 geometry: 4x).
- **Logit round-trip**: the [B, N, Tq, S] f32 logits + softmax
  intermediates materialize between two einsums instead of living in
  VMEM.

This kernel fuses the scan FlashAttention-style over a grid
(B, K // kb, S // Sb): each program instance owns one slot's block of
``kb`` KV heads for one [Sb] KV tile. The S grid axis IS the KV tiling:
TPU grid steps run sequentially with the innermost axis fastest, so the
online-softmax state (m, l, acc) lives in VMEM scratch carried across
the S steps of each (slot, head-block) — initialized at s == 0,
finalized into the output at the last tile — while Pallas pipelines the
next tile's HBM->VMEM copy behind the current tile's compute. Every
[Sb, H] K/V slab is read exactly once (all Tq window rows and all
G = N/K query heads sharing a KV head ride the same read) — GQA via
layout, no repeat, any capacity.

Two TPU lowering rules shape the blocking (trailing two block dims must
be (8, 128)-tile-aligned or span the array):

- K/V live as [B, S, K, H], so a one-head block (trailing dims (1, H))
  is illegal — heads move in blocks of ``kb`` (8 when K divides into
  8-groups, else all of K). A layout transpose instead would
  materialize a full KV-cache copy every substep, which is the exact
  HBM cost this kernel exists to avoid.
- The [B, Tq, S] mask's trailing dim is the S tile, so Sb must be a
  multiple of 128 or span S (``_pick_sb``).
Large prefill tiles stay on the flash kernel
(``ops/flash_attention.py``); this covers the decode half VERDICT r4 #8
called out (the reference has no decode engine to compare against — its
serving path is fixed-shape vision forwards,
``293-project/src/scheduler.py:435-452``).

Masking: windows arrive as a [B, 1, Tq, S] boolean (True = attend —
``models/decoder.py::decode_mask`` for Tq == 1, ``verify_step``'s
per-row scatter windows for the speculative path), streamed as int8
[Tq, S] per row — Tq bytes per KV position vs the 2H-byte K/V read they
gate.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_dynamic_batching_tpu.ops import tile_math
from ray_dynamic_batching_tpu.ops.tile_math import VMEM_BLOCK_BUDGET_BYTES

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept
# either so the kernel lowers on both sides of the rename.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams",
                           getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30

# Windows past this ride the flash kernel (>= 16) or XLA (9..15): wide
# windows are prefill-shaped work where the flash kernel's query-tiled
# grid wins; this kernel's per-program q/scratch footprint grows with
# window * G.
MAX_WINDOW_FOR_KERNEL = 8


def _decode_kernel(
    q_ref,      # [1, kb, Tq*G, H]   rows ordered (t, g)
    k_ref,      # [1, Sb, kb, H]     this grid step's KV tile
    v_ref,      # [1, Sb, kb, H]
    mask_ref,   # [1, Tq, Sb] int8, or None
    ks_ref,     # [1, kb, Sb] f32 per-row K scales (int8 cache), or None
    vs_ref,     # [1, kb, Sb] f32 per-row V scales, or None
    o_ref,      # [1, kb, Tq*G, H]
    m_ref,      # VMEM scratch [kb, Tq*G] f32 — carried across S steps
    l_ref,      # VMEM scratch [kb, Tq*G] f32
    acc_ref,    # VMEM scratch [kb, Tq*G, H] f32
    *,
    scale: float,
    num_s: int,
    window: int,
):
    R = q_ref.shape[2]          # Tq * G
    Sb = k_ref.shape[1]
    G = R // window

    # Head-invariant per-tile validity: every head block shares the
    # per-(t, g)-row window. Sb divides S (``_pick_sb``), so there is no
    # ragged tail to mask.
    if mask_ref is not None:
        mvals = mask_ref[0, :, :] != 0  # [Tq, Sb]
        # [Tq, Sb] -> one row per (t, g): g shares t's window.
        valid = jnp.broadcast_to(
            mvals[:, None, :], (window, G, Sb)
        ).reshape(R, Sb)
    else:
        valid = None
    _scan_tile(
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref,
        acc_ref, valid=valid, scale=scale, num_s=num_s,
    )


def _scan_tile(
    q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
    *, valid, scale: float, num_s: int,
):
    """One KV tile of the online-softmax scan — the body shared by the
    slab kernel (S-axis tiles, mask-derived ``valid``) and the paged
    kernel (page-table tiles, length-derived ``valid``): init scratch at
    tile 0, accumulate this tile per head, finalize into the output on
    the last tile. The math being ONE function is what keeps the paged
    and slab kernels numerically identical."""
    kb = q_ref.shape[1]
    R = q_ref.shape[2]
    H = q_ref.shape[3]
    compute_dtype = q_ref.dtype  # int8 codes cast exactly (<= +-127)
    s_idx = pl.program_id(2)

    @pl.when(s_idx == 0)
    def _init():
        m_ref[...] = jnp.full((kb, R), NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros((kb, R), jnp.float32)
        acc_ref[...] = jnp.zeros((kb, R, H), jnp.float32)

    for h in range(kb):         # static unroll: this program's KV heads
        q = q_ref[0, h, :, :]        # [R, H]
        k_tile = k_ref[0, :, h, :]   # [Sb, H]
        v_tile = v_ref[0, :, h, :]
        if ks_ref is not None:
            # Int8 cache: the per-row scale factors OUT of both dots —
            # scores scale per key column, and V's scale rides on p —
            # so the kernel reads 1-byte codes and never materializes
            # an H-wide dequantized tile (this is the bandwidth win).
            k_tile = k_tile.astype(compute_dtype)
            v_tile = v_tile.astype(compute_dtype)
        s = jax.lax.dot_general(
            q, k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [R, Sb] f32
        if ks_ref is not None:
            s = s * ks_ref[0, h, :][None, :]
        if valid is not None:
            s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[h, :]
        l_prev = l_ref[h, :]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))  # [R]
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])  # [R, Sb]
        m_ref[h, :] = m_cur
        l_ref[h, :] = l_prev * alpha + jnp.sum(p, axis=1)
        if vs_ref is not None:
            p = p * vs_ref[0, h, :][None, :]
        acc_ref[h, :, :] = acc_ref[h, :, :] * alpha[:, None] + (
            jax.lax.dot_general(
                p.astype(compute_dtype), v_tile,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )  # [R, H]

    @pl.when(s_idx == num_s - 1)
    def _finalize():
        for h in range(kb):
            l = l_ref[h, :]
            # A fully-masked row (inactive spec rows are steered out of
            # bounds; their outputs are never consumed) -> zeros, not NaN.
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, h, :, :] = (
                acc_ref[h, :, :] / l[:, None]
            ).astype(o_ref.dtype)


def _pick_heads_block(K: int) -> int:
    """Largest-tile-legal KV-head block: trailing-two block dims on the
    [B, S, K, H] cache are (kb, H), so kb must be a multiple of 8 or span
    K exactly (the TPU lowering's divisible-by-(8,128)-or-equal rule)."""
    if K % 8 == 0 and K > 8:
        return 8
    return K


# The VMEM budget and the padded-footprint model live in
# ops/tile_math.py, SHARED with the static vmem-budget checker
# (tools/lint) — one implementation, so the static model and this
# runtime picker cannot drift. H=64 geometries (gpt2_medium,
# llama_tiny, whisper heads) double under 128-lane padding; budgeting
# the raw H undercounted the K/V block ~2x and picked tiles whose true
# double-buffered footprint blew the ~16 MB/core this file assumes —
# the exact bug class the shared model (and its lint rule) pins down.


def _pick_sb(S: int, kb: int, H: int, kv_itemsize: int,
             with_mask: bool, target: Optional[int] = None,
             with_scales: bool = False) -> int:
    """Largest KV tile Sb that (a) divides S, (b) is mask-tile-legal
    (a multiple of 128, or S itself — the mask block's trailing dim is
    Sb), and (c) fits the VMEM budget with double buffering. A
    ``target`` caps the tile when a legal tile under it exists
    (callers tune pipeline granularity; tests force multi-tile scans
    on small capacities)."""
    def tile_bytes(sb: int) -> int:
        return tile_math.decode_tile_bytes(
            sb, kb, H, kv_itemsize, with_mask, with_scales=with_scales
        )

    cands = [S] + [
        sb for sb in range((S // 128) * 128, 127, -128) if S % sb == 0
    ]
    cands = [sb for sb in cands
             if tile_bytes(sb) <= VMEM_BLOCK_BUDGET_BYTES]
    if not cands:
        return 0  # no legal tile: caller declines to XLA
    if target is not None:
        capped = [sb for sb in cands if sb <= target]
        if capped:
            return max(capped)
    return max(cands)


@functools.partial(
    jax.jit, static_argnames=("scale", "sb", "window", "interpret")
)
def _decode_attention(
    q: jax.Array,      # [B, K, Tq*G, H]  rows ordered (t, g)
    k: jax.Array,      # [B, S, K, H]
    v: jax.Array,
    mask: Optional[jax.Array],  # [B, Tq, S] int8, or None
    k_scale: Optional[jax.Array],  # [B, S, K] f32 (int8 cache), or None
    v_scale: Optional[jax.Array],
    *,
    scale: float,
    sb: int,
    window: int,
    interpret: bool,
) -> jax.Array:
    B, K, R, H = q.shape
    S = k.shape[1]
    kb = _pick_heads_block(K)
    num_s = S // sb
    in_specs = [
        pl.BlockSpec((1, kb, R, H), lambda b, j, s: (b, j, 0, 0)),
        pl.BlockSpec((1, sb, kb, H), lambda b, j, s: (b, s, j, 0)),
        pl.BlockSpec((1, sb, kb, H), lambda b, j, s: (b, s, j, 0)),
    ]
    args = [q, k, v]
    has_mask = mask is not None
    has_scales = k_scale is not None
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, window, sb), lambda b, j, s: (b, 0, s))
        )
        args.append(mask)
    if has_scales:
        # Scales travel as [B, K, S]: block (1, kb, sb) has trailing
        # dims (kb -> 8-sublane pad, sb = lane multiple of 128) — pad
        # free. A [B, S, K, 1] layout would be tile-legal but its
        # (kb, 1) trailing dims pad to (8, 128): a ~128x VMEM blowup
        # invisible to export-based lowering tests. The transpose copies
        # only the S*K*4-byte scale plane (<0.1% of the cache read).
        scale_spec = pl.BlockSpec(
            (1, kb, sb), lambda b, j, s: (b, j, s)
        )
        in_specs += [scale_spec, scale_spec]
        args += [k_scale.transpose(0, 2, 1), v_scale.transpose(0, 2, 1)]

    def kernel(q_ref, k_ref, v_ref, *rest):
        idx = 0
        mask_ref = rest[idx] if has_mask else None
        idx += 1 if has_mask else 0
        ks_ref = rest[idx] if has_scales else None
        vs_ref = rest[idx + 1] if has_scales else None
        idx += 2 if has_scales else 0
        o_ref, m_ref, l_ref, acc_ref = rest[idx:idx + 4]
        _decode_kernel(
            q_ref, k_ref, v_ref, mask_ref, ks_ref, vs_ref,
            o_ref, m_ref, l_ref, acc_ref,
            scale=scale, num_s=num_s, window=window,
        )

    out_dtype = q.dtype
    return pl.pallas_call(
        kernel,
        grid=(B, K // kb, num_s),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, kb, R, H), lambda b, j, s: (b, j, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, R, H), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((kb, R), jnp.float32),
            pltpu.VMEM((kb, R), jnp.float32),
            pltpu.VMEM((kb, R, H), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)


@functools.partial(
    jax.jit, static_argnames=("scale", "window", "interpret")
)
def _paged_decode_attention(
    q: jax.Array,          # [B, K, Tq*G, H]  rows ordered (t, g)
    k: jax.Array,          # [P, ps, K, H] page pool
    v: jax.Array,
    page_table: jax.Array,  # [B, NP] int32, sentinel P
    lengths: jax.Array,     # [B] int32 — row t attends pos <= lengths[b]+t
    k_scale: Optional[jax.Array],  # [P, K, ps] f32 (int8 pool), or None
    v_scale: Optional[jax.Array],
    *,
    scale: float,
    window: int,
    interpret: bool,
) -> jax.Array:
    B, K, R, H = q.shape
    G = R // window
    P, ps = k.shape[0], k.shape[1]
    NP = page_table.shape[1]
    kb = _pick_heads_block(K)
    has_scales = k_scale is not None

    # The page axis IS the KV tiling: grid step (b, j, p) streams slot
    # b's p-th page — whichever physical page the PREFETCHED table names
    # (sentinel/garbage entries clamp to a real page; the length bound
    # masks everything they could contribute). Pages replace the slab
    # kernel's S-axis tiles one-for-one, so the online-softmax scratch
    # carry works unchanged.
    def kv_index(b, j, p, pt, ln):
        return (jnp.minimum(pt[b, p], P - 1), 0, j, 0)

    in_specs = [
        pl.BlockSpec((1, kb, R, H), lambda b, j, p, pt, ln: (b, j, 0, 0)),
        pl.BlockSpec((1, ps, kb, H), kv_index),
        pl.BlockSpec((1, ps, kb, H), kv_index),
    ]
    args = [q, k, v]
    if has_scales:
        scale_spec = pl.BlockSpec(
            (1, kb, ps),
            lambda b, j, p, pt, ln: (jnp.minimum(pt[b, p], P - 1), j, 0),
        )
        in_specs += [scale_spec, scale_spec]
        args += [k_scale, v_scale]

    def kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest):
        ks_ref = rest[0] if has_scales else None
        vs_ref = rest[1] if has_scales else None
        o_ref, m_ref, l_ref, acc_ref = rest[2 if has_scales else 0:][:4]
        b = pl.program_id(0)
        p = pl.program_id(2)
        # In-kernel STAIRCASE validity from the prefetched lengths: page
        # p covers logical positions [p*ps, (p+1)*ps); window row t (row
        # r = t*G + g) attends pos <= lengths[b] + t — the spec-verify
        # window rule, whose Tq == 1 degenerate case is exactly the slab
        # decode_mask bound. No mask array is streamed at all.
        pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (R, ps), 1)
        t_of_row = jax.lax.broadcasted_iota(jnp.int32, (R, ps), 0) // G
        valid = pos <= len_ref[b] + t_of_row
        _scan_tile(
            q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref,
            acc_ref, valid=valid, scale=scale, num_s=NP,
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, K // kb, NP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, kb, R, H), lambda b, j, p, pt, ln: (b, j, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((kb, R), jnp.float32),
            pltpu.VMEM((kb, R), jnp.float32),
            pltpu.VMEM((kb, R, H), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, K, R, H), q.dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(page_table, lengths, *args)


def paged_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    page_table: jax.Array,
    kv_lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    mesh: Optional[Any] = None,
    mesh_axis: str = "tp",
) -> Optional[jax.Array]:
    """Fused page-table decode attention; returns None when the shapes
    aren't the paged decode pattern (caller falls back to the explicit
    gather — same decline contract as :func:`decode_attention`).

    q [B, Tq, N, H] with Tq <= MAX_WINDOW_FOR_KERNEL; k/v [P, ps, K, H]
    page pools with K dividing N; page_table [B, NP] int32 (sentinel P =
    unallocated); kv_lengths [B]. Window row t attends logical positions
    <= kv_lengths[b] + t — the STAIRCASE rule of the speculative-verify
    window (``models/decoder.py::paged_window_mask`` owns it), whose
    Tq == 1 case is exactly the plain-decode ``decode_mask`` bound.
    ``k_scale``/``v_scale`` [P, ps, K] enable the int8-pool path.

    Eligibility is the lane-alignment + VMEM-budget contract of
    ``ops/tile_math.py``: the page IS the KV tile, so its streamed
    footprint (``paged_tile_bytes``) must fit the shared budget
    double-buffered, and the page size must be a 128-lane multiple (the
    int8 scale tile's lane dim is the page). The static ``vmem-budget``
    lint rule re-evaluates this same model over the BlockSpecs above.

    ``mesh`` (a TP serving slice; ROADMAP item 2) runs the SAME kernel
    per shard under ``shard_map`` over ``mesh_axis``: q and the pools
    split on the kv-head axis (the slab TP layout — pages are
    shard-invariant, so the page table and lengths replicate), each
    shard scans its own head slice with the shared ``_scan_tile`` body,
    and the VMEM guard budgets the PER-SHARD block
    (``tile_math.shard_heads`` — a head-sharded kernel's bytes divide
    by the TP degree). Declines (None) when the head axis does not
    divide — replicated heads fall back to the gather path, which GSPMD
    partitions from the pool's NamedSharding.
    """
    if q.ndim != 4 or k.ndim != 4:
        return None
    B, Tq, N, H = q.shape
    if not (1 <= Tq <= MAX_WINDOW_FOR_KERNEL):
        return None  # wide windows are prefill-shaped: gather/flash path
    P, ps, K, Hk = k.shape
    if Hk != H or v.shape != k.shape or K == 0 or N % K != 0:
        return None
    if page_table.ndim != 2 or page_table.shape[0] != B:
        return None
    if kv_lengths.shape != (B,):
        return None
    if (k_scale is None) != (v_scale is None):
        return None
    if k_scale is not None and (
            k_scale.shape != (P, ps, K) or v_scale.shape != (P, ps, K)):
        return None
    if not tile_math.lane_aligned_page(ps):
        return None
    tp = 1
    if mesh is not None:
        tp = int(mesh.shape.get(mesh_axis, 1))
        if tp > 1 and (K % tp != 0 or N % tp != 0):
            return None  # heads replicate under this mesh: gather path
    # Per-shard footprint: each shard owns K/tp kv heads, so the guard
    # budgets the block the kernel will ACTUALLY stream on one core.
    k_local = tile_math.shard_heads(K, tp)
    kb = _pick_heads_block(k_local)
    G = N // K
    if tile_math.paged_tile_bytes(
            ps, kb, H, k.dtype.itemsize,
            with_scales=k_scale is not None,
            # G is shard-invariant: a shard keeps N/tp query per K/tp kv
            # heads, so each head block still carries Tq*G window rows.
            window=Tq, G=G,
    ) > VMEM_BLOCK_BUDGET_BYTES:
        return None  # page too fat for VMEM double-buffering: gather path
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else H ** -0.5
    # Rows ordered (t, g) per kv head: [B, Tq, K, G, H] ->
    # [B, K, Tq*G, H] (Tq == 1 collapses to the historical layout).
    q_r = q.reshape(B, Tq, K, G, H).transpose(0, 2, 1, 3, 4).reshape(
        B, K, Tq * G, H
    )
    ks = vs = None
    if k_scale is not None:
        # [P, ps, K] -> [P, K, ps]: the page becomes the (lane) trailing
        # dim of the scale tile — pad-free because pages are lane-aligned
        # (the [B, S, K, 1]-layout ~128x blowup documented on the slab
        # path is the same trap this transpose avoids).
        ks = k_scale.transpose(0, 2, 1)
        vs = v_scale.transpose(0, 2, 1)
    if tp > 1:
        out = _paged_decode_attention_tp(
            mesh, mesh_axis, q_r, k, v, page_table.astype(jnp.int32),
            kv_lengths.astype(jnp.int32), ks, vs,
            scale=float(scale), window=int(Tq), interpret=bool(interpret),
        )
    else:
        out = _paged_decode_attention(
            q_r, k, v, page_table.astype(jnp.int32),
            kv_lengths.astype(jnp.int32), ks, vs,
            scale=float(scale), window=int(Tq), interpret=bool(interpret),
        )
    return out.reshape(B, K, Tq, G, H).transpose(0, 2, 1, 3, 4).reshape(
        B, Tq, N, H
    )


def _paged_decode_attention_tp(
    mesh, axis: str, q_r, k, v, page_table, kv_lengths, ks, vs,
    *, scale: float, window: int, interpret: bool,
):
    """The TP wrapper: ``shard_map`` the paged kernel over the mesh's
    ``axis`` with q/pools split on the kv-head dim and the page
    table/lengths replicated (page indices are shard-invariant). Each
    shard's call is the ordinary single-device kernel on its head
    slice — numerics are per-head, so the sharded result is exactly the
    unsharded one re-laid-out."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    args = [q_r, k, v, page_table, kv_lengths]
    in_specs = [
        P(None, axis, None, None),   # q rows split by kv head
        P(None, None, axis, None),   # k pool: heads split, pages whole
        P(None, None, axis, None),
        P(None, None),               # page table: replica-global
        P(None),                     # lengths: replica-global
    ]
    has_scales = ks is not None
    if has_scales:
        args += [ks, vs]
        in_specs += [P(None, axis, None), P(None, axis, None)]

    def local(q_l, k_l, v_l, pt, ln, *rest):
        ks_l = rest[0] if has_scales else None
        vs_l = rest[1] if has_scales else None
        return _paged_decode_attention(
            q_l, k_l, v_l, pt, ln, ks_l, vs_l,
            scale=scale, window=window, interpret=interpret,
        )

    return shard_map(
        local, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, axis, None, None),
        check_rep=False,
    )(*args)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    block_k: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> Optional[jax.Array]:
    """Fused small-window attention; returns None when the shapes aren't
    the decode pattern (caller falls back to flash/XLA, same contract as
    ``flash_attention.flash_attention``).

    q [B, Tq, N, H] with Tq <= MAX_WINDOW_FOR_KERNEL; k/v [B, S, K, H]
    with K dividing N; mask None or broadcastable to [B, 1, Tq, S]
    (True = attend). The KV-head grouping matches ``_xla_attention``'s
    ``jnp.repeat`` semantics: query head n reads kv head n // (N // K).

    ``k_scale``/``v_scale`` [B, S, K] enable the int8-cache path: k/v
    hold codes, the kernel reads 1-byte tiles and applies the per-row
    scales inside the dots (``KVCache`` docstring) — the decode scan's
    bandwidth win.
    """
    if q.ndim != 4 or k.ndim != 4:
        return None
    B, Tq, N, H = q.shape
    _, S, K, _ = k.shape
    if not (1 <= Tq <= MAX_WINDOW_FOR_KERNEL):
        return None
    if K == 0 or N % K != 0 or v.shape != k.shape:
        return None
    if (k_scale is None) != (v_scale is None):
        return None
    if k_scale is not None and (
            k_scale.shape != (B, S, K) or v_scale.shape != (B, S, K)):
        return None
    G = N // K
    if mask is not None:
        if mask.shape[-1] != S:
            return None
        try:
            mask = jnp.broadcast_to(
                mask, (B, 1, Tq, S)
            ).reshape(B, Tq, S).astype(jnp.int8)
        except (TypeError, ValueError):
            # e.g. a per-head [B, N, Tq, S] mask: not this kernel's
            # pattern — decline so the caller falls back to XLA, which
            # handles arbitrary masks.
            return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # KV tile: must divide S (a ragged tile's block would clamp and
    # re-read shifted rows), be mask-tile-legal, and fit VMEM
    # double-buffered. 0 = no legal tile (pathological S) -> XLA.
    sb = _pick_sb(S, _pick_heads_block(K), H, k.dtype.itemsize,
                  mask is not None, target=block_k,
                  with_scales=k_scale is not None)
    if sb == 0:
        return None
    scale = scale if scale is not None else H ** -0.5
    # Rows ordered (t, g) per kv head: [B, Tq, K, G, H] -> [B, K, Tq*G, H].
    q_r = q.reshape(B, Tq, K, G, H).transpose(0, 2, 1, 3, 4).reshape(
        B, K, Tq * G, H
    )
    out = _decode_attention(
        q_r, k, v, mask, k_scale, v_scale,
        scale=float(scale), sb=int(sb), window=int(Tq),
        interpret=bool(interpret),
    )
    return out.reshape(B, K, Tq, G, H).transpose(0, 2, 1, 3, 4).reshape(
        B, Tq, N, H
    )
