"""Shared static model of the engine's hot-path jit programs.

Single source of truth for WHICH jit entry points exist on the decode
hot path, what their donation contracts are, what shape grid each one
retraces over, and which warmup routine is responsible for compiling it
before serving. Consumed by BOTH enforcers (the ``tile_math`` /
``concurrency.LOCK_RANKS`` pattern applied to the jit layer):

- at runtime, ``DecodeEngine._warmup_impl`` cross-checks the compile
  ledger (``utils/compile_ledger.py``) against :func:`required_for` —
  a registered program its arm needs that warmup did NOT compile is a
  hard error at startup, not a 20-40s XLA stall mid-serving;
- statically, three rdb-lint rules load this module standalone
  (importlib, no jax): ``jit-retrace-hazard`` analyses the registered
  impl bodies (decode.py jits them via ``jax.jit(self._impl)`` at init,
  invisible to the decorator-based host-sync rule),
  ``donation-discipline`` pins every ``jax.jit`` creation site's
  ``donate_argnums``/``static_argnums`` to the contract recorded here,
  and ``warmup-coverage`` requires every registered program to be
  invoked inside its declared ``warmed_by`` routine (and every
  UNregistered ``self._*_fn = jax.jit(...)`` assignment to either join
  the registry or carry a reasoned pragma).

Deliberately dependency-free (no jax import): the linter loads this
module standalone so ``python -m tools.lint`` stays fast and runs in
environments without an accelerator stack.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Tuple

# Engine arms a program serves. An engine instance activates a subset
# (see required_for); warmup is judged per-arm, so the mono engine is
# not required to warm chunk programs it never dispatches.
ARM_ALWAYS = "always"            # every engine configuration
ARM_CHUNKED_PAGED = "chunked_paged"  # chunked_prefill and paged
ARM_CHUNKED_SLAB = "chunked_slab"    # chunked_prefill, slab cache
ARM_MONO = "mono"                # legacy monolithic admission
ARM_SPEC = "spec"                # draft model attached
ARM_SPEC_MONO = "spec_mono"      # draft model AND mono admission


@dataclass(frozen=True)
class JitProgram:
    """One hot-path jit entry point and its contracts.

    ``attr`` is the engine attribute (or factory method) holding the
    compiled callable; ``impl`` the method jit-wrapped at creation.
    ``donate``/``static`` are the EXACT ``donate_argnums`` /
    ``static_argnums`` the creation site must pass — ``donated`` names
    the buffers those positions carry, so a contract change has to say
    what it un-donates. ``grid`` documents the shape axes the program
    retraces over; ``warmed_by`` names the warmup routine that must
    invoke ``attr`` (empty iff lazy, with a mandatory ``lazy_reason``).
    """

    name: str
    attr: str
    impl: str
    donate: Tuple[int, ...] = ()
    static: Tuple[int, ...] = ()
    donated: Tuple[str, ...] = ()
    grid: str = ""
    warmed_by: str = ""
    lazy_reason: str = ""
    arm: str = ARM_ALWAYS

    def __post_init__(self) -> None:
        if not self.warmed_by and not self.lazy_reason:
            raise ValueError(
                f"jit program {self.name!r}: not warmed and no "
                "lazy_reason — every hot-path program is either warmed "
                "or explains why a first-hit compile is acceptable"
            )


HOT_PROGRAMS: Tuple[JitProgram, ...] = (
    JitProgram(
        name="decode_step",
        attr="_decode_fn", impl="_decode_impl",
        donate=(1, 8), static=(3,),
        donated=("cache", "counts"),
        grid="horizon in {1, ttft_horizon, decode_horizon}",
        warmed_by="_warmup_decode", arm=ARM_ALWAYS,
    ),
    JitProgram(
        name="chunk_prefill",
        attr="_chunk_paged_fn", impl="_chunk_group_paged_impl",
        donate=(2,),
        donated=("pool cache",),
        grid="(bucket x group) via _admit_group_sizes",
        warmed_by="_warmup_impl", arm=ARM_CHUNKED_PAGED,
    ),
    JitProgram(
        name="prefill_group",
        attr="_prefill_fn", impl="_prefill_impl",
        donate=(2,),
        donated=("cache",),
        grid="(bucket x group) via _admit_group_sizes",
        warmed_by="_warmup_prefill_groups", arm=ARM_MONO,
    ),
    JitProgram(
        name="prefill_group_paged",
        attr="_prefill_fn", impl="_prefill_paged_impl",
        donate=(2,),
        donated=("cache",),
        grid="(bucket x group) via _admit_group_sizes",
        warmed_by="_warmup_prefill_groups", arm=ARM_MONO,
    ),
    JitProgram(
        name="spec_verify",
        attr="_spec_fn", impl="_spec_impl",
        donate=(1, 2),
        donated=("cache", "draft cache"),
        grid="one shape: (num_slots x spec_window)",
        warmed_by="_warmup_decode", arm=ARM_SPEC,
    ),
    JitProgram(
        name="draft_catchup",
        attr="_draft_catchup_fn", impl="_draft_catchup_impl",
        donate=(1,),
        donated=("draft cache",),
        grid="window h in {1, ttft_horizon, decode_horizon}",
        warmed_by="_warmup_decode", arm=ARM_SPEC,
    ),
    JitProgram(
        name="draft_prefill",
        attr="_draft_prefill_fn", impl="_draft_prefill_impl",
        donate=(2,),
        donated=("draft cache",),
        grid="(bucket x group) via _admit_group_sizes",
        warmed_by="_warmup_decode", arm=ARM_SPEC_MONO,
    ),
    JitProgram(
        name="zero_counts",
        attr="_zero_counts_fn", impl="_reset_counts",
        donate=(0,),
        donated=("counts",),
        grid="one shape: (num_slots x vocab)",
        warmed_by="_warmup_decode", arm=ARM_ALWAYS,
    ),
    # --- registered-lazy programs (legacy/slab arms and cold session
    # moves). Each lazy_reason is load-bearing: warmup-coverage treats an
    # UNregistered lazy jit as a finding, so adding a factory means
    # writing down why its first-hit compile is acceptable.
    JitProgram(
        name="long_chunk",
        attr="_long_prefill_fns", impl="_prefill_chunk_impl",
        donate=(3,),
        donated=("row cache",),
        grid="chunk = largest bucket (one per engine)",
        warmed_by="_warmup_impl", arm=ARM_CHUNKED_SLAB,
    ),
    JitProgram(
        name="long_commit",
        attr="_long_prefill_fns", impl="_commit_long_impl",
        donate=(0,),
        donated=("cache",),
        grid="chunk = largest bucket (one per engine)",
        warmed_by="_warmup_impl", arm=ARM_CHUNKED_SLAB,
    ),
    JitProgram(
        name="long_commit_paged",
        attr="_long_prefill_fns", impl="_commit_long_paged_impl",
        donate=(0,),
        donated=("cache",),
        grid="chunk = largest bucket",
        lazy_reason="mono-paged engines reach long fills only for "
        "prompts past the largest bucket, which may never arrive; the "
        "persistent compilation cache absorbs the first-hit cost",
        arm=ARM_MONO,
    ),
    JitProgram(
        name="prefix_seed",
        attr="_long_prefill_fns", impl="_seed_prefix_impl",
        donate=(0,),
        donated=("row cache",),
        grid="one shape per chunk size",
        lazy_reason="prefix-cache CoW seeding rides the long-fill path; "
        "slab engines with no long prompts never dispatch it",
        arm=ARM_MONO,
    ),
    JitProgram(
        name="prefix_extract",
        attr="_long_prefill_fns", impl="_extract_prefix_impl",
        static=(1,),
        grid="one shape per (chunk, prefix length bucket)",
        lazy_reason="runs once per prefix PUBLISH (cold, off the decode "
        "turn); publishing is already an amortized slow path",
        arm=ARM_MONO,
    ),
    JitProgram(
        name="paged_seed",
        attr="_paged_seed_fn", impl="_seed_paged_impl",
        donate=(0,),
        donated=("row cache",),
        grid="one shape: (1 x row_cap)",
        lazy_reason="legacy mono-paged session/prefix seeding only; the "
        "chunked-universal arm seeds pages-direct through the chunk "
        "program and never calls this",
        arm=ARM_MONO,
    ),
    JitProgram(
        name="session_seed",
        attr="_session_fns", impl="_seed_session_impl",
        donate=(0,),
        donated=("row cache",),
        grid="one shape: (1 x max_len)",
        lazy_reason="slab session continuation only — sessions may "
        "never be enabled; first turn-2 on a restart pays it once",
        arm=ARM_MONO,
    ),
    JitProgram(
        name="session_extract",
        attr="_session_fns", impl="_extract_row_impl",
        grid="one shape: (1 x max_len)",
        lazy_reason="runs once per session FINISH (cold, off the "
        "decode turn) to pin the finished row",
        arm=ARM_MONO,
    ),
    JitProgram(
        name="draft_long_chunk",
        attr="_draft_long_fill", impl="chunk_impl",
        donate=(3,),
        donated=("draft row cache",),
        grid="chunk = largest bucket",
        lazy_reason="spec engines see long prompts rarely; the draft's "
        "chunk program compiles once at the first long admission and "
        "the chunk-stall bound already prices that turn",
        arm=ARM_SPEC,
    ),
    JitProgram(
        name="draft_long_commit",
        attr="_draft_long_fill", impl="commit_row",
        donate=(0,),
        donated=("draft cache",),
        grid="chunk = largest bucket",
        lazy_reason="paired with draft_long_chunk — same cold path",
        arm=ARM_SPEC,
    ),
)

_BY_NAME: Dict[str, JitProgram] = {p.name: p for p in HOT_PROGRAMS}


def program(name: str) -> JitProgram:
    return _BY_NAME[name]


def program_names() -> Tuple[str, ...]:
    return tuple(_BY_NAME)


def warmed_programs() -> Tuple[JitProgram, ...]:
    return tuple(p for p in HOT_PROGRAMS if p.warmed_by)


def lazy_programs() -> Tuple[JitProgram, ...]:
    return tuple(p for p in HOT_PROGRAMS if not p.warmed_by)


def registered_impls() -> FrozenSet[str]:
    """Impl callable names the registry knows — the retrace rule's
    analysis set and warmup-coverage's registration check."""
    return frozenset(p.impl for p in HOT_PROGRAMS)


def registered_attrs() -> FrozenSet[str]:
    """Engine attributes / factories that legally hold jit programs."""
    return frozenset(p.attr for p in HOT_PROGRAMS)


def donation_contract(impl: str) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """(donate_argnums, static_argnums) the creation site wrapping
    ``impl`` must pass. KeyError for unregistered impls — callers decide
    whether unknown means 'not hot path' or 'finding'."""
    for p in HOT_PROGRAMS:
        if p.impl == impl:
            return (p.donate, p.static)
    raise KeyError(impl)


def required_for(chunked_prefill: bool, paged: bool,
                 has_draft: bool) -> Tuple[JitProgram, ...]:
    """Warmed programs an engine configuration MUST compile during
    warmup — the runtime coverage check's ground truth. Mirrors the
    dispatch in ``DecodeEngine._warmup_impl``: chunked+paged warms the
    chunk program, slab-chunked the long chunk/commit pair, mono the
    (bucket x group) prefill grid; spec engines add verify + catch-up,
    and only MONO spec engines add the draft group-prefill grid."""
    arms = {ARM_ALWAYS}
    if chunked_prefill and paged:
        arms.add(ARM_CHUNKED_PAGED)
    elif chunked_prefill:
        arms.add(ARM_CHUNKED_SLAB)
    else:
        arms.add(ARM_MONO)
    if has_draft:
        arms.add(ARM_SPEC)
        if not chunked_prefill:
            arms.add(ARM_SPEC_MONO)
    out = []
    for p in warmed_programs():
        if p.arm not in arms:
            continue
        # The prefill_group pair is impl-dispatched on paged-ness; only
        # one of the two compiles on a given engine.
        if p.name == "prefill_group" and paged:
            continue
        if p.name == "prefill_group_paged" and not paged:
            continue
        # Slab-arm long programs: _commit_long_impl serves slab engines,
        # _commit_long_paged_impl is registered lazy for mono-paged.
        if p.name == "long_commit" and paged:
            continue
        out.append(p)
    return tuple(out)
