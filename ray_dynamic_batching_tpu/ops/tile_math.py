"""Shared TPU tile-padding / VMEM-footprint math.

Single source of truth for the padded-footprint model used by BOTH the
runtime KV-tile picker (``ops/decode_attention.py::_pick_sb``) and the
static ``vmem-budget`` checker (``tools/lint``). PR 1 fixed a real bug
where the hand-computed double-buffered footprint undercounted lane
padding (H=64 geometries looked ~2x smaller than their true in-VMEM
size and busted the per-core budget); keeping one implementation here is
what stops the static model and the runtime picker from drifting apart
the same way.

The model (Mosaic's VMEM tiling rules):

- a block's SUBLANE (second-to-last) dim pads up to the dtype's tile
  height — f32 8, bf16 16, int8 32 (``SUBLANE_PACK``);
- its LANE (last) dim pads up to a multiple of 128;
- leading dims multiply unpadded;
- Pallas double-buffers streamed blocks (``DOUBLE_BUFFER``), so the
  in-flight footprint of a grid step is twice the padded block sum.

Deliberately dependency-free (no jax import): the linter loads this
module standalone so ``python -m tools.lint`` stays fast and runs in
environments without an accelerator stack.
"""

from __future__ import annotations

from typing import Sequence

# Dtype tile height by itemsize: sublane packing halves as elements
# shrink, so SUBLANE_PACK[itemsize] * itemsize == 32 bytes for every
# supported dtype. (That identity is why f32 is the worst-case itemsize
# for a padded footprint: ceil(n/8) >= ceil(n/16) >= ceil(n/32).)
SUBLANE_PACK = {4: 8, 2: 16, 1: 32}

LANE = 128

# Pallas pipelines the next tile's HBM->VMEM copy behind the current
# tile's compute: two buffers per streamed block are resident at once.
DOUBLE_BUFFER = 2

# Per-grid-step VMEM ceiling for a kernel call's streamed blocks
# (~16 MB VMEM/core): footprints count the FULLY padded tiles (sublane
# AND 128-lane dims) double-buffered, so the budget honestly bounds the
# in-VMEM bytes and can sit close to the core limit — q/out blocks and
# f32 accumulator scratch riding alongside are small. 15 MB keeps
# whisper's only legal decode tile (whole S=448, ~14.7 MB true) while
# rejecting the H=64 whole-S tiles the old raw-H budget wrongly
# accepted (~16.8 MB true).
VMEM_BLOCK_BUDGET_BYTES = 15 * 1024 * 1024


def sublane_pack(itemsize: int) -> int:
    """Dtype tile height (rows) for an itemsize; unknown itemsizes get
    the f32 pack (f32 is the worst case per byte, see SUBLANE_PACK)."""
    return SUBLANE_PACK.get(itemsize, 8)


def pad_lane(n: int) -> int:
    """Lane (last) dim padded up to a multiple of 128."""
    return -(-n // LANE) * LANE


def pad_sublane(n: int, itemsize: int) -> int:
    """Sublane (second-to-last) dim padded up to the dtype tile height."""
    pack = sublane_pack(itemsize)
    return -(-n // pack) * pack


def padded_block_bytes(block_shape: Sequence[int], itemsize: int) -> int:
    """True in-VMEM bytes of ONE BlockSpec block: both trailing dims
    padded (sublane to the dtype tile height, lane to 128), leading dims
    multiplied unpadded. A 1-D block is a single lane row (sublane 1)."""
    dims = [int(d) for d in block_shape]
    if not dims:
        return itemsize
    lane = pad_lane(dims[-1])
    sub = pad_sublane(dims[-2] if len(dims) >= 2 else 1, itemsize)
    lead = 1
    for d in dims[:-2]:
        lead *= d
    return lead * sub * lane * itemsize


def pages_for(length: int, page_size: int) -> int:
    """Pages needed to hold ``length`` cached positions (ceil division).
    Shared by the engine's allocator bookkeeping and the sim's occupancy
    accounting so the two can never disagree about footprint."""
    if length <= 0:
        return 0
    return -(-int(length) // int(page_size))


def shard_heads(num_kv_heads: int, tp: int) -> int:
    """Per-shard KV-head count under ``tp``-way tensor-parallel head
    sharding: K/tp when tp divides K, else K — an indivisible head axis
    REPLICATES instead of sharding (``parallel/mesh._feasible_spec``),
    so every shard still streams the full head set. Shared by the
    runtime kernel guards (a head-sharded paged kernel's VMEM bytes
    divide by the TP degree) and the standalone-loaded vmem-budget
    lint model, with an agreement pin test so the two cannot drift."""
    tp = int(tp or 1)
    if tp > 1 and num_kv_heads % tp == 0:
        return num_kv_heads // tp
    return num_kv_heads


def spec_scratch_pages(length: int, spec_window: int,
                       page_size: int, capacity: int) -> int:
    """Pages a speculative verify round needs a slot's table to cover:
    the round writes the ``spec_window`` (= spec_tokens + 1) positions
    ``[length, length + spec_window)``, clamped to the slot's logical
    ``capacity``. Shared by the engine's scratch-page reservation
    (``DecodeEngine._reserve_spec_scratch``) and the admission headroom
    rule (``pages_for(len + spec_tokens + 1)``), so the two can never
    disagree about a round's page demand."""
    return pages_for(min(int(length) + int(spec_window), int(capacity)),
                     page_size)


def lane_aligned_page(page_size: int) -> bool:
    """A KV page is tile-legal iff its size is a LANE multiple: the int8
    scale tile streams as [1, kb, page_size] with the page as its lane
    dim, so an unaligned page silently pads every scale tile in VMEM."""
    return page_size > 0 and page_size % LANE == 0


def paged_tile_bytes(
    page_size: int,
    kb: int,
    H: int,
    kv_itemsize: int,
    with_scales: bool = False,
    window: int = 1,
    G: int = 1,
) -> int:
    """Double-buffered VMEM footprint of one PAGED decode-attention grid
    step's streamed blocks — the model the paged kernel's runtime guard
    budgets against and the static ``vmem-budget`` checker re-evaluates
    (the paged analogue of :func:`decode_tile_bytes`):

    - K and V page tiles [1, page_size, kb, H] at the cache itemsize
      (trailing dims (kb, H), same padding story as the slab tile);
    - optional K/V scale tiles [1, kb, page_size] f32 (page_size is the
      LANE dim — hence :func:`lane_aligned_page`);
    - NO mask tile: validity is computed in-kernel from the prefetched
      per-slot lengths, so the paged path streams no mask at all.

    ``window`` > 1 (the speculative-verify Tq == k+1 window) adds the
    SCRATCH-HEADROOM term: the q/out blocks ([1, kb, window*G, H]) and
    the f32 online-softmax accumulator ([kb, window*G, H] VMEM scratch)
    grow with the window's row count, and for decode's Tq == 1 they are
    the small riders the base model documents away — a wide window makes
    them first-class. ``window == 1`` returns EXACTLY the historical
    value (agreement pins in tests/test_lint.py stay byte-stable).
    """
    kv = 2 * padded_block_bytes((1, page_size, kb, H), kv_itemsize)
    scale_b = (
        2 * padded_block_bytes((1, kb, page_size), 4) if with_scales else 0
    )
    total = DOUBLE_BUFFER * (kv + scale_b)
    if window > 1:
        rows = int(window) * max(1, int(G))
        qo = 2 * padded_block_bytes((1, kb, rows, H), kv_itemsize)
        acc = padded_block_bytes((kb, rows, H), 4)  # f32 scratch, single
        total += DOUBLE_BUFFER * qo + acc
    return total


def decode_tile_bytes(
    sb: int,
    kb: int,
    H: int,
    kv_itemsize: int,
    with_mask: bool,
    with_scales: bool = False,
    window: int = 1,
) -> int:
    """Double-buffered VMEM footprint of one decode-attention grid
    step's streamed blocks — the exact model ``_pick_sb`` budgets
    against (and the static checker re-evaluates):

    - K and V tiles [1, sb, kb, H] at the cache itemsize (trailing dims
      (kb, H): kb pads to the dtype tile height, H to 128 lanes — the
      H=64 lane padding PR 1's fix made honest);
    - optional mask tile [1, window, sb] int8 (window <= 8 pads to the
      int8 tile height 32; sb is the lane dim);
    - optional K/V scale tiles [1, kb, sb] f32.
    """
    kv = 2 * padded_block_bytes((1, sb, kb, H), kv_itemsize)
    mask_b = padded_block_bytes((1, window, sb), 1) if with_mask else 0
    scale_b = 2 * padded_block_bytes((1, kb, sb), 4) if with_scales else 0
    return DOUBLE_BUFFER * (kv + mask_b + scale_b)
