"""Attention ops with backend dispatch (XLA reference now, Pallas on TPU).

The reference framework has no attention of its own (it serves fixed-shape
vision models through torch); attention enters via the north-star LLM configs.
This module is the single place models get attention from, so the engine can
swap the XLA einsum reference for the fused Pallas kernel
(:mod:`ray_dynamic_batching_tpu.ops.flash_attention`) on TPU without touching
model code.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_BACKEND = "auto"  # "auto" | "xla" | "pallas"
# (mesh, axis) when sequence parallelism is active. ContextVar, not a module
# global: concurrent jit traces (e.g. a serve replica warming up while a
# train step traces) must not observe each other's mesh.
_SP_CTX: contextvars.ContextVar[Optional[Tuple]] = contextvars.ContextVar(
    "sequence_parallel_ctx", default=None
)
# (mesh, axis) when a TP serving slice is active (ROADMAP item 2): the
# paged decode kernel must run per-shard under shard_map — GSPMD cannot
# partition a pallas_call on its own — so the engine names its slice
# here and the dispatcher threads it into the kernel wrapper. Same
# ContextVar discipline (and the same enter-inside-the-traced-function
# contract) as the sequence-parallel context above.
_TP_CTX: contextvars.ContextVar[Optional[Tuple]] = contextvars.ContextVar(
    "tensor_parallel_ctx", default=None
)


def set_attention_backend(backend: str) -> None:
    global _BACKEND
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown attention backend {backend!r}")
    _BACKEND = backend


@contextlib.contextmanager
def sequence_parallel(mesh, axis: str = "sp"):
    """While active (including during jit tracing), :func:`self_attention`
    routes through the ring-attention kernel over the mesh's ``axis`` when
    that axis has more than one device. The trace-time context is baked into
    the compiled program, so enter it inside the jitted step function."""
    token = _SP_CTX.set((mesh, axis))
    try:
        yield
    finally:
        _SP_CTX.reset(token)


@contextlib.contextmanager
def tensor_parallel(mesh, axis: str = "tp"):
    """While active (including during jit tracing), the PAGED decode
    read routes the Pallas kernel through its per-shard ``shard_map``
    wrapper over the mesh's ``axis`` (``paged_decode_attention``'s
    ``mesh`` parameter): q and the page pools split on the kv-head dim,
    page table and lengths stay replicated — page indices are
    shard-invariant. The non-kernel paths need no context: the gather
    fallback is plain jnp, which GSPMD partitions from the pool's
    NamedSharding. Enter it inside the jitted step function, exactly
    like :func:`sequence_parallel`."""
    token = _TP_CTX.set((mesh, axis))
    try:
        yield
    finally:
        _TP_CTX.reset(token)


def self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    token_mask: Optional[jax.Array] = None,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Self-attention over a full (un-cached) sequence; q/k/v [B, T, *, H],
    token_mask [B, T] True = valid. Under an active :func:`sequence_parallel`
    context with sp > 1 this dispatches to ring attention (sequence sharded
    over the ``sp`` mesh axis); otherwise dense attention with the causal +
    padding mask built here."""
    ctx = _SP_CTX.get()
    if ctx is not None:
        mesh, axis = ctx
        if mesh.shape.get(axis, 1) > 1:
            from ray_dynamic_batching_tpu.ops.ring_attention import (
                ring_self_attention,
            )

            return ring_self_attention(
                mesh, q, k, v, token_mask, causal=causal, scale=scale,
                axis=axis,
            )
    mask = None
    if token_mask is not None:
        mask = token_mask[:, None, None, :].astype(bool)
    return dot_product_attention(q, k, v, causal=causal, mask=mask, scale=scale)


def _use_pallas() -> bool:
    if _BACKEND == "xla":
        return False
    if _BACKEND == "pallas":
        return True
    return jax.default_backend() == "tpu"


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    page_table: Optional[jax.Array] = None,
    kv_lengths: Optional[jax.Array] = None,
) -> jax.Array:
    """Multi-head attention.

    Shapes: q [B, Tq, N, H], k/v [B, Tk, K, H] with K == N or K dividing N
    (grouped-query attention: each group of N//K query heads shares a kv head).
    mask: broadcastable to [B, 1, Tq, Tk], True = attend.

    ``k_scale``/``v_scale`` [B, Tk, K]: k/v are int8 KV-cache codes
    (models/decoder.py::KVCache). The decode kernel consumes the codes
    directly (1-byte scan, scales applied inside the dots); every other
    path dequantizes first and proceeds as usual.

    ``page_table`` [B, NP] + ``kv_lengths`` [B] switch to the PAGED
    decode read: k/v (and scales) are page POOLS ([P, ps, K, H] /
    [P, ps, K]) shared by all slots, and each slot's logical KV run is
    the table-ordered gather of its pages. The Pallas paged kernel
    fuses that gather into the KV scan (no logical-view materialization
    in HBM); everywhere else an explicit gather rebuilds the slab view
    and re-enters this function — one mask/dequant rule, so paged and
    slab reads are token-exact against each other.
    """
    if page_table is not None:
        return _paged_attention(
            q, k, v, page_table, kv_lengths, mask=mask, scale=scale,
            k_scale=k_scale, v_scale=v_scale,
        )
    if _use_pallas():
        if not causal:
            # Small query windows — plain decode (Tq == 1), speculative
            # verify (Tq == k+1), small prefill buckets: the fused
            # KV-scan kernel — GQA via layout (no jnp.repeat of the
            # cache read), online softmax in VMEM
            # (ops/decode_attention.py). Window semantics ride the
            # explicit mask, so only non-causal calls qualify; the
            # kernel itself owns the eligibility band and declines
            # wider windows.
            from ray_dynamic_batching_tpu.ops import decode_attention

            out = decode_attention.decode_attention(
                q, k, v, mask=mask, scale=scale,
                k_scale=k_scale, v_scale=v_scale,
            )
            if out is not None:
                return out
        if k_scale is not None:
            k, v = _dequantize(k, k_scale, q.dtype), _dequantize(
                v, v_scale, q.dtype)
            k_scale = v_scale = None
        from ray_dynamic_batching_tpu.ops import flash_attention

        out = flash_attention.flash_attention(
            q, k, v, causal=causal, mask=mask, scale=scale
        )
        if out is not None:
            return out
    if k_scale is not None:
        k, v = _dequantize(k, k_scale, q.dtype), _dequantize(
            v, v_scale, q.dtype)
    return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale)


def _paged_attention(
    q: jax.Array,
    k: jax.Array,              # [P, ps, K, H] page pool (one layer)
    v: jax.Array,
    page_table: jax.Array,     # [B, NP] int32, sentinel P = unallocated
    kv_lengths: jax.Array,     # [B] valid logical prefix (attend <= len)
    *,
    mask: Optional[jax.Array],
    scale: Optional[float],
    k_scale: Optional[jax.Array],   # [P, ps, K] or None
    v_scale: Optional[jax.Array],
) -> jax.Array:
    """Paged decode read: fused page-table KV scan on the Pallas path,
    explicit gather back to the slab view otherwise (the token-exact
    fallback — identical values land in identical logical positions, and
    the shared ``decode_mask`` rule bounds what is attended)."""
    if mask is not None:
        raise ValueError(
            "paged attention derives its window from kv_lengths; an "
            "explicit mask on this path means a caller mixed the slab "
            "and paged conventions"
        )
    if _use_pallas():
        from ray_dynamic_batching_tpu.ops import decode_attention

        tp_ctx = _TP_CTX.get()
        mesh_kwargs = {}
        if tp_ctx is not None:
            tp_mesh, tp_axis = tp_ctx
            if tp_mesh.shape.get(tp_axis, 1) > 1:
                mesh_kwargs = {"mesh": tp_mesh, "mesh_axis": tp_axis}
        out = decode_attention.paged_decode_attention(
            q, k, v, page_table, kv_lengths, scale=scale,
            k_scale=k_scale, v_scale=v_scale, **mesh_kwargs,
        )
        if out is not None:
            return out
    # Gather fallback: rebuild each slot's logical KV run [B, S, K, H]
    # (S = NP * ps) and re-enter the slab path. Sentinel/garbage pages
    # clamp to a real page, then the length mask voids their positions —
    # the same never-attended-garbage invariant the slab cache relies on.
    # Tq > 1 is the speculative-verify window: the STAIRCASE mask (row t
    # attends <= lengths + t, paged_window_mask — the same rule the
    # kernel computes in-VMEM from the prefetched lengths).
    from ray_dynamic_batching_tpu.models.decoder import paged_window_mask

    P = k.shape[0]
    safe = jnp.minimum(page_table, P - 1)
    B, NP = page_table.shape
    ps = k.shape[1]

    def logical(pages):
        g = pages[safe]  # [B, NP, ps, ...]
        return g.reshape((B, NP * ps) + pages.shape[2:])

    k_g, v_g = logical(k), logical(v)
    ks_g = vs_g = None
    if k_scale is not None:
        ks_g, vs_g = logical(k_scale), logical(v_scale)
    win = paged_window_mask(kv_lengths, NP * ps, q.shape[1])
    return dot_product_attention(
        q, k_g, v_g, mask=win, scale=scale, k_scale=ks_g, v_scale=vs_g,
    )


def _dequantize(codes: jax.Array, scales: jax.Array,
                dtype) -> jax.Array:
    # Deferred import (decoder imports this module): the dequant rule
    # has exactly one definition, next to the quantizer it inverts.
    from ray_dynamic_batching_tpu.models.decoder import dequantize_kv

    return dequantize_kv(codes, scales, dtype)


def _xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    mask: Optional[jax.Array],
    scale: Optional[float],
) -> jax.Array:
    B, Tq, N, H = q.shape
    _, Tk, K, _ = k.shape
    if K != N:
        assert N % K == 0, f"query heads {N} not divisible by kv heads {K}"
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    scale = scale if scale is not None else H ** -0.5
    # [B, N, Tq, Tk] logits in f32 for numerical stability on bf16 inputs.
    logits = jnp.einsum("bqnh,bknh->bnqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), k=Tk - Tq)
        logits = jnp.where(causal_mask[None, None, :, :], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


# NOTE: decode-path masking lives in models/decoder.py (decode_mask) — the
# single owner of the KV-cache attention-window convention.
