"""Ring attention: sequence-parallel exact attention over the ``sp`` mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5
"long-context / sequence parallelism — absent in the reference"; §2.4 lists
SP/CP as a from-scratch TPU design item). Design follows the blockwise ring
schedule (Liu et al., ring attention): the sequence is sharded contiguously
over ``sp`` devices; each device keeps its query block resident and rotates
the key/value blocks one hop around the ICI ring per step with
``jax.lax.ppermute``, accumulating exact softmax attention online
(flash-attention style running max / running sum), so no device ever
materializes the full [T, T] score matrix and peak memory stays
O(T_local^2 / sp) while compute stays exact.

Meant to be called INSIDE ``shard_map`` (the framework wraps it via
:func:`ring_self_attention`); communication is ppermute over ICI, which XLA
overlaps with the per-block matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30  # finite sentinel: keeps the online-softmax NaN-free


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    kv_mask: Optional[jax.Array] = None,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name``.

    Per-shard shapes: q [B, Tq, N, H]; k/v [B, Tk, K, H] with K == N or
    K dividing N (GQA); kv_mask [B, Tk] True = valid key. The global sequence
    is the concatenation of the per-device chunks in axis order, so global
    position = chunk_index * T_local + local_offset (right-padded batches:
    padding keys are masked via kv_mask, padding queries produce zeros and
    are expected to be masked by the caller's loss/readout).
    """
    B, Tq, N, H = q.shape
    _, Tk, K, _ = k.shape
    if K != N:
        assert N % K == 0, f"query heads {N} not divisible by kv heads {K}"
    scale = scale if scale is not None else H ** -0.5

    size = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    q_pos = idx * Tq + jnp.arange(Tq)  # global query positions [Tq]

    m = jnp.full((B, N, Tq), _NEG_INF, dtype=jnp.float32)  # running max
    l = jnp.zeros((B, N, Tq), dtype=jnp.float32)           # running denom
    acc = jnp.zeros((B, Tq, N, H), dtype=jnp.float32)      # running numer
    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), dtype=bool)
    kv_mask = kv_mask.astype(bool)

    perm = [(j, (j + 1) % size) for j in range(size)]

    def block_update(carry, k_blk, v_blk, mask_blk, src):
        m, l, acc = carry
        if K != N:
            # GQA expand here, AFTER the ppermute, so the ring only ships
            # the K kv heads (not the N-head expansion) over ICI
            k_blk = jnp.repeat(k_blk, N // K, axis=2)
            v_blk = jnp.repeat(v_blk, N // K, axis=2)
        k_pos = src * Tk + jnp.arange(Tk)  # global key positions [Tk]
        logits = jnp.einsum(
            "bqnh,bknh->bnqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        valid = mask_blk[:, None, None, :]  # [B,1,1,Tk]
        if causal:
            valid = jnp.logical_and(
                valid, (k_pos[None, None, None, :] <= q_pos[None, None, :, None])
            )
        logits = jnp.where(valid, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # Explicitly zero masked probabilities: with the finite -1e30
        # sentinel, exp(logits - m_new) would be 1 (not 0) for a fully
        # masked row whose running max is still the sentinel.
        p = jnp.where(valid, jnp.exp(logits - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)  # rescale of previous accumulation
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bnqk,bknh->bqnh", p, v_blk.astype(jnp.float32)
        )
        return m_new, l, acc

    # size is a traced value only under pmap; under shard_map over a Mesh it
    # is static (mesh shape is known), so a Python loop unrolls the ring.
    n_steps = int(size) if isinstance(size, int) else None
    if n_steps is None:  # pragma: no cover - defensive; shard_map gives static
        raise ValueError("ring_attention requires a static mesh axis size")

    carry = (m, l, acc)
    for step in range(n_steps):
        src = (idx - step) % n_steps
        if causal and step > 0:
            # Skip compute for blocks wholly in the future of every local
            # query (min key pos > max query pos) — about half the ring
            # steps under causal masking; the ppermute still rotates.
            carry = jax.lax.cond(
                src * Tk > idx * Tq + (Tq - 1),
                lambda c, *_: c,
                block_update,
                carry, k, v, kv_mask, src,
            )
        else:
            carry = block_update(carry, k, v, kv_mask, src)
        if step != n_steps - 1:
            k, v, kv_mask = (
                jax.lax.ppermute(x, axis_name, perm) for x in (k, v, kv_mask)
            )
    m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


@functools.lru_cache(maxsize=None)
def _ring_fn(mesh: Mesh, axis: str, causal: bool, scale: Optional[float]):
    """Partial-manual shard_map: only the sequence axis is manual (the ring);
    dp/tp sharding of batch and heads stays under GSPMD inside the body."""
    qspec = P(None, axis, None, None)
    mspec = P(None, axis)
    fn = functools.partial(
        ring_attention, axis_name=axis, causal=causal, scale=scale
    )
    return jax.shard_map(
        lambda q, k, v, msk: fn(q, k, v, kv_mask=msk),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, mspec),
        out_specs=qspec,
        axis_names=frozenset({axis}),
    )


def ring_self_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    token_mask: Optional[jax.Array] = None,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    axis: str = "sp",
) -> jax.Array:
    """Global-shape entry point: shard_maps :func:`ring_attention` over the
    mesh (batch→dp, sequence→``axis``, heads→tp when divisible)."""
    B, T, N, H = q.shape
    sp = mesh.shape.get(axis, 1)
    if T % sp != 0:
        raise ValueError(f"sequence length {T} not divisible by {axis}={sp}")
    if token_mask is None:
        token_mask = jnp.ones((B, T), dtype=bool)
    fn = _ring_fn(mesh, axis, causal, scale)
    return fn(q, k, v, token_mask.astype(bool))
