"""Simulated control plane — the REAL scheduler logic at virtual time.

This is deliberately thin: the pieces that decide anything are the live
modules themselves, reused through their clock seams —

- rate estimation: ``engine/rates.py`` ``RateRegistry`` with
  ``clock=VirtualClock.now_s`` (same sliding window, same asymmetric
  change thresholds, same cold-start semantics);
- the replan decision: ``scheduler/replan.decide_replan`` — the SAME
  pure function ``LiveScheduler.rebalance`` applies (no-drift pin in
  ``tests/test_sim.py``);
- the audit trail: ``scheduler/audit.AuditLog`` with ``now=`` injected,
  so a simulated run's decision records are shaped (and dashboard-
  renderable) exactly like a live run's, just with virtual timestamps.

Only the monitor thread is re-expressed: a recurring event at
``monitoring_interval_s`` of VIRTUAL time instead of ``Event.wait``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.engine.rates import RateRegistry
from ray_dynamic_batching_tpu.engine.request import (
    DEFAULT_QOS_CLASS,
    DEFAULT_TENANT,
)
from ray_dynamic_batching_tpu.scheduler.audit import AuditLog
from ray_dynamic_batching_tpu.scheduler.nexus import (
    NodePlan,
    SquishyBinPacker,
)
from ray_dynamic_batching_tpu.scheduler.replan import (
    ModelEntry,
    decide_replan,
    sessions_for,
    weighted_attainment,
)
from ray_dynamic_batching_tpu.serve.retrybudget import (
    RetryBudget,
    RetryBudgetPolicy,
)
from ray_dynamic_batching_tpu.serve.grayhealth import (
    GrayHealthMonitor,
    GrayHealthPolicy,
    ratio_observations,
)
from ray_dynamic_batching_tpu.serve.observatory import (
    ObservatoryPolicy,
    SLOObservatory,
)
from ray_dynamic_batching_tpu.sim.clock import EventLoop, VirtualClock
from ray_dynamic_batching_tpu.sim.engine import SimEngine
from ray_dynamic_batching_tpu.sim.queue import (
    SimQueueManager,
    SimRequest,
)


class SimScheduler:
    """The simulated scheduling domain (live ``LiveScheduler`` shape)."""

    def __init__(
        self,
        packer: SquishyBinPacker,
        engines: List[SimEngine],
        queues: SimQueueManager,
        loop: EventLoop,
        clock: VirtualClock,
        monitoring_interval_s: float = 5.0,
        rate_threshold: float = 0.05,
        rate_decrease_multiplier: float = 2.0,
        rate_window_s: float = 10.0,
        rate_min_span_s: float = 0.0,
        gray_policy: Optional[GrayHealthPolicy] = None,
        observatory_policy: Optional[ObservatoryPolicy] = None,
    ) -> None:
        self.packer = packer
        self.engines = list(engines)
        self.queues = queues
        self.loop = loop
        self.clock = clock
        self.monitoring_interval_s = monitoring_interval_s
        self.rate_threshold = rate_threshold
        self.rate_decrease_multiplier = rate_decrease_multiplier
        self.rate_min_span_s = rate_min_span_s  # live cold-window guard
        self.rates = RateRegistry(window_s=rate_window_s, clock=clock.now_s)
        self.audit = AuditLog("sim", now=clock.now_s)
        self._models: Dict[str, ModelEntry] = {}
        self._current_plan: List[NodePlan] = []
        self._monitor_until_ms = 0.0
        self._dead_engines: set = set()
        # Gray-failure monitoring (the SAME detector the serve tier
        # runs — serve/grayhealth.py — on the virtual clock, fed with
        # observed/expected step-latency ratios instead of ms so a
        # multi-model engine grades model-agnostically). None = disabled:
        # canon scenarios stay byte-identical.
        self.gray: Optional[GrayHealthMonitor] = None
        if gray_policy is not None:
            self.gray = GrayHealthMonitor(
                "sim", policy=gray_policy, clock=clock.now_s
            )
            self.gray.audit = self.audit
            for e in self.engines:
                e.track_ratios = True
        self._gray_ejected: set = set()
        # Per-engine ratio window over the last N monitor TICKS: a
        # 10x-slowed engine finishes ~10x FEWER batches per tick, so
        # grading only each tick's drain would starve detection of the
        # very samples that prove the slowdown — while a sample-count
        # window would go stale the moment a probation replan idles the
        # engine. Tick-bounding gives both: slow evidence stays visible
        # across ticks, and a heal flushes within window_ticks.
        self._gray_window_ticks = 3
        self._gray_windows: Dict[str, List[List[float]]] = {}
        self.schedule_changes = 0
        self.schedule_log: List[Dict] = []
        # Optional serve.admission.AdmissionController built on the
        # VIRTUAL clock (the live module, reused — not re-expressed):
        # submit() consults it pre-queue exactly like the live proxies,
        # and the monitor tick feeds its governor the same depth/
        # compliance signals ServeController._control_step does.
        self.admission = None
        # (model, qos_class) -> rejected-at-admission count (the third
        # accounting category: offered = rejected + enqueued outcomes).
        self.admission_rejected: Dict[Tuple[str, str], int] = {}
        # SLO observatory (serve/observatory.py — the SAME classes the
        # live controller ticks, on the virtual clock). None = disabled:
        # canon scenarios stay byte-identical. The fidelity price fn
        # reads the CURRENT plan's profile rows — the planner's belief,
        # jitter- and degradation-free — so a seeded mispricing drifts
        # engine.step and only engine.step.
        self.observatory: Optional[SLOObservatory] = None
        if observatory_policy is not None:
            self.observatory = SLOObservatory(
                "sim", policy=observatory_policy, clock=clock.now_s,
                price=self._fidelity_price,
            )
            self.observatory.audit = self.audit
        # --- client-retry model (ISSUE 19) --------------------------------
        # None = disabled: no stale-shed hook is installed, canon
        # scenarios stay byte-identical. enable_retries() turns stale
        # sheds into budgeted resubmissions — the amplification loop
        # that makes overload metastable when unbounded.
        self._retry_policy: Optional[RetryBudgetPolicy] = None
        self.retry_max_attempts = 0
        self.retry_backoff_ms = 0.0
        self.retry_budgets: Dict[str, RetryBudget] = {}
        self.retry_submitted: Dict[str, int] = {}
        # Per-class resubmission counts so the conservation identity
        # extends under retries: offered + resubmitted_classes ==
        # admission_rejected + enqueued, per (model, class).
        self.retry_submitted_classes: Dict[str, Dict[str, int]] = {}
        self.retry_denied: Dict[str, int] = {}
        self.retry_exhausted: Dict[str, int] = {}
        # Windowed weighted attainment sampled at monitor ticks — the
        # recovery timeline the metastability pin grades.
        self.attainment_timeline: List[Dict] = []
        self._attainment_prev: Dict[str, Dict[str, Dict[str, float]]] = {}
        # --- query-of-death tracking (ISSUE 19) ---------------------------
        # Engines report isolations here (the sim twin of the replica ->
        # router quarantine gossip); repeats of a quarantined poison_id
        # are fenced at submit, never reaching a queue.
        self._poison_quarantined: set = set()
        self.poison_injected: Dict[str, int] = {}
        self.poison_fenced: Dict[str, int] = {}
        self.poison_isolations: List[Dict] = []
        for e in self.engines:
            e.on_poison = self._note_poison

    # --- registration (live register_model contract) ----------------------
    def register_model(self, name: str, slo_ms: float,
                       seq_len: int = 0, mesh_shape: str = "1x1",
                       spec: str = "off", spec_acceptance: float = 0.0,
                       spec_tokens: int = 4,
                       prefill_chunk_ms: float = 0.0) -> None:
        if name not in self.packer.profiles:
            raise KeyError(f"no batch profile for model {name!r}")
        self._models[name] = ModelEntry(
            name, slo_ms, seq_len, mesh_shape,
            spec=spec, spec_acceptance=spec_acceptance,
            spec_tokens=spec_tokens, prefill_chunk_ms=prefill_chunk_ms,
        )

    # --- ingress (live submit_request: demand recorded before enqueue) ----
    def submit(self, model: str, qos_class: str = DEFAULT_QOS_CLASS,
               tenant: str = DEFAULT_TENANT,
               prefill_ms: float = 0.0,
               poison_id: Optional[str] = None,
               retry_attempt: int = 0) -> bool:
        entry = self._models.get(model)
        if entry is None:
            return False
        if poison_id is not None:
            self.poison_injected[model] = (
                self.poison_injected.get(model, 0) + 1
            )
            if poison_id in self._poison_quarantined:
                # Front-door fence (live QuarantineRegistry.check): a
                # quarantined query of death is rejected at admission —
                # it never reaches a queue, never poisons a batch twice.
                self.poison_fenced[model] = (
                    self.poison_fenced.get(model, 0) + 1
                )
                # The fence IS a front-door rejection (live: 4xx from the
                # proxy) — count it so per-class conservation holds.
                key = (model, qos_class)
                self.admission_rejected[key] = (
                    self.admission_rejected.get(key, 0) + 1
                )
                return False
        if self.admission is not None:
            ok, _retry_after_s = self.admission.admit(
                model, tenant, qos_class
            )
            if not ok:
                # Turned away pre-queue: no demand signal either — the
                # planner plans for admitted load, mirroring the live
                # proxy-before-scheduler order.
                key = (model, qos_class)
                self.admission_rejected[key] = (
                    self.admission_rejected.get(key, 0) + 1
                )
                return False
        self.rates.record(model)
        if self.observatory is not None:
            self.observatory.note_arrivals(model)
        if self._retry_policy is not None and retry_attempt == 0:
            # First attempts FUND the budget (work-conserving fraction
            # of real demand); retries only spend it.
            self._retry_budget(model).record_first_attempt()
        return self.queues.queue(model).add_request(
            SimRequest(
                model=model,
                arrival_ms=self.clock.now_ms(),
                slo_ms=entry.slo_ms,
                seq_len=entry.seq_len,
                qos_class=qos_class,
                tenant=tenant,
                prefill_ms=prefill_ms,
                retry_attempt=retry_attempt,
                poison_id=poison_id,
            )
        )

    # --- client-retry model (ISSUE 19) ------------------------------------
    def enable_retries(self, max_attempts: int = 3,
                       backoff_ms: float = 50.0,
                       budget_fraction: Optional[float] = None,
                       budget_window: int = 512,
                       min_first_attempts: int = 16) -> None:
        """Turn stale sheds into client resubmissions with fresh
        deadlines — the retry amplification loop. Each shed consults a
        per-model :class:`RetryBudget` (the live serve-tier class, not a
        re-expression): ``budget_fraction=None`` models naive clients
        (unbounded retries — the metastable control arm), a fraction
        bounds retry volume to that share of first-attempt demand, and
        the admission governor's congested state zeroes it entirely."""
        if max_attempts < 1:
            raise ValueError("retry max_attempts must be >= 1")
        if backoff_ms < 0:
            raise ValueError("retry backoff_ms must be >= 0")
        self._retry_policy = RetryBudgetPolicy(
            fraction=budget_fraction, window=budget_window,
            min_first_attempts=min_first_attempts,
        )
        self.retry_max_attempts = int(max_attempts)
        self.retry_backoff_ms = float(backoff_ms)
        self.queues.on_stale = self._on_stale_shed
        for q in self.queues.queues().values():
            q.on_stale = self._on_stale_shed

    def _retry_budget(self, model: str) -> RetryBudget:
        budget = self.retry_budgets.get(model)
        if budget is None:
            budget = RetryBudget(f"sim:{model}", self._retry_policy)
            self.retry_budgets[model] = budget
        return budget

    def _on_stale_shed(self, queue, req: SimRequest) -> None:
        """Stale-shed hook: the client saw a deadline miss and retries —
        unless it has exhausted its attempts or the budget denies the
        resubmission (the defense that keeps recovery monotone)."""
        attempt = req.retry_attempt
        if attempt + 1 >= self.retry_max_attempts:
            self.retry_exhausted[req.model] = (
                self.retry_exhausted.get(req.model, 0) + 1
            )
            return
        if not self._retry_budget(req.model).try_spend("retry"):
            self.retry_denied[req.model] = (
                self.retry_denied.get(req.model, 0) + 1
            )
            return
        self.retry_submitted[req.model] = (
            self.retry_submitted.get(req.model, 0) + 1
        )
        per_cls = self.retry_submitted_classes.setdefault(req.model, {})
        per_cls[req.qos_class] = per_cls.get(req.qos_class, 0) + 1
        delay_ms = max(self.retry_backoff_ms * (2 ** attempt), 0.001)
        self.loop.schedule_in(
            delay_ms,
            lambda m=req.model, q=req.qos_class, t=req.tenant,
            pm=req.prefill_ms, p=req.poison_id, a=attempt + 1:
            self.submit(m, qos_class=q, tenant=t, prefill_ms=pm,
                        poison_id=p, retry_attempt=a),
        )

    def _note_poison(self, poison_id: str, model: str) -> None:
        """Engine-side bisection condemned a query of death: quarantine
        its id cluster-wide (the sim twin of the registry gossip) so a
        repeat submission is fenced at the front door."""
        new = poison_id not in self._poison_quarantined
        self._poison_quarantined.add(poison_id)
        self.poison_isolations.append({
            "t_s": round(self.clock.now_s(), 6),
            "model": model,
            "poison_id": poison_id,
            "new": new,
        })
        if new:
            self.audit.record(
                "poison_quarantine",
                key=model,
                observed={"poison_id": poison_id},
                diff={"quarantined": poison_id},
                note="query of death isolated by batch bisection; "
                     "repeats fence at the front door",
            )

    # --- scheduling: decide via the shared pure step, apply to sim engines
    def rebalance(
        self,
        rates: Optional[Dict[str, float]] = None,
        trigger: str = "manual",
    ) -> List[NodePlan]:
        rates = rates if rates is not None else self.rates.rates()
        # A gray-EJECTED engine leaves planning exactly like a dead one
        # (the chip is reclaimed); probation prices as fractional
        # capacity via decide_replan's derate pass.
        alive = [
            e for e in self.engines
            if e.healthy() and e.engine_id not in self._gray_ejected
        ]
        factors = None
        if self.gray is not None:
            factors = [self.gray.capacity_factor(e.engine_id)
                       for e in alive]
        # Slice geometry (same surface LiveScheduler reads): widths/
        # shapes of the surviving schedulable units, so the shared
        # decide step degrades TP sessions and matches by width.
        widths = [e.width for e in alive]
        meshes = [e.mesh_shape for e in alive]
        decision = decide_replan(
            self.packer,
            [frozenset(e.models) for e in alive],
            sessions_for(self._models, rates),
            rates,
            capacity_factors=factors,
            engine_widths=widths,
            engine_meshes=meshes,
        )
        for engine, node_plan in zip(alive, decision.assignment):
            if node_plan is not None:
                engine.assign(node_plan)
            elif engine.models:
                engine.assign(NodePlan())  # idle this engine
        self._current_plan = decision.plan
        self.rates.mark_scheduled(rates)
        self.schedule_changes += 1
        self.schedule_log.append(
            {
                "ts": self.clock.now_s(),
                "rates": dict(rates),
                "nodes": [n.describe() for n in decision.plan],
            }
        )
        self.audit.record(trigger, **decision.audit_fields())
        return decision.plan

    # --- monitor loop as a recurring event --------------------------------
    def start_monitoring(self, until_ms: float) -> None:
        """Arm the recurring monitor. The first tick fires 1 ms BEFORE
        the interval boundary: the rate window buckets by integer
        second, so a monitor aligned exactly on second boundaries would
        always read an empty partial bucket — a systematic ~1/window
        under-read no live deployment (whose phase is arbitrary) is
        pinned to. The -1 ms phase reads full buckets instead.

        interval <= 0 means monitoring is DISABLED (only warm-start /
        manual rebalances happen) — re-arming at zero delay would spin
        the event loop at one virtual instant forever."""
        if self.monitoring_interval_s <= 0:
            return
        self._monitor_until_ms = until_ms
        self.loop.schedule_in(
            max(self.monitoring_interval_s * 1000.0 - 1.0, 1.0),
            self._on_monitor,
        )

    def check_engine_health(self) -> bool:
        """Mirror of ``LiveScheduler.check_engine_health``: detect newly
        dead engines at the monitor tick (same detection lag the live
        control loop pays) and replan over survivors — failure-driven,
        so it bypasses the rate cold-window guard.

        Slice deaths additionally RE-FORM: a dead chip takes its whole
        slice (SliceDeadError semantics), but the other chips in the
        gang are good silicon — they come back as the widest
        power-of-two sub-slices that fit (a broken 1x4 re-forms as a
        1x2 + a 1x1), so the heal replan runs over the TRUE surviving
        geometry and ``degrade_sessions`` can re-shape a TP=4 model to
        its TP=2 profile row on the re-formed half-slice."""
        newly_dead = [
            e for e in self.engines
            if e.engine_id not in self._dead_engines and not e.healthy()
        ]
        if not newly_dead:
            return False
        observed: Dict = {}
        slices: Dict = {}
        for e in newly_dead:
            self._dead_engines.add(e.engine_id)
            if e.width <= 1:
                continue
            reformed = self._reform_slices(e)
            slices[e.engine_id] = {
                "width": e.width,
                "dead_chip": e.failed_chip,
                "reformed": [
                    {"engine": n.engine_id, "width": n.width}
                    for n in reformed
                ],
            }
        observed["dead_engines"] = sorted(self._dead_engines)
        if slices:
            observed["dead_slices"] = slices
        self.audit.record(
            "engine_dead",
            observed=observed,
            diff={"removed": [e.engine_id for e in newly_dead]},
            note="engine death detected by monitor; replan over survivors",
        )
        self.rebalance(trigger="heal")
        return True

    def _reform_slices(self, dead: SimEngine) -> List[SimEngine]:
        """Regroup a dead slice's surviving chips into the widest
        power-of-two sub-slices and enroll them as fresh schedulable
        units (started, gray-tracked when monitoring is armed). The
        next rebalance — fired by the caller — places over them."""
        survivors = dead.surviving_chips()
        reformed: List[SimEngine] = []
        serial = 0
        while survivors:
            w = 1
            while w * 2 <= len(survivors):
                w *= 2
            chips, survivors = survivors[:w], survivors[w:]
            engine = SimEngine(
                f"{dead.engine_id}r{serial}",
                self.queues,
                self.packer.profiles,
                self.loop,
                self.clock,
                idle_wait_ms=dead.idle_wait_ms,
                jitter_rng=dead.jitter_rng,
                occupancy_model=dead.occupancy_model,
                occupancy_floor=dead.occupancy_floor,
                width=w,
                chip_ids=chips,
            )
            serial += 1
            if self.gray is not None:
                engine.track_ratios = True
            engine.on_poison = self._note_poison
            self.engines.append(engine)
            engine.start()
            reformed.append(engine)
        return reformed

    def check_gray_health(self) -> bool:
        """The gray analogue of :meth:`check_engine_health`: tick the
        detector with each engine's fresh observed/expected step ratios
        and replan when any verdict changed (probation reprices the
        engine as fractional capacity; ejection reclaims it like a
        death). Returns True when a gray replan fired."""
        if self.gray is None:
            return False
        live = [e for e in self.engines
                if e.healthy() and e.engine_id not in self._gray_ejected]
        # Synthetic probation probes: the probation replan may have
        # emptied an engine's plan; the LIVE router still probes a
        # probationed replica (one request per probe window). The sim
        # twin: one probe per tick reading the engine's current cost
        # ratio (stall included — a stall-only straggler must not grade
        # healthy), so a heal stays observable.
        probes = {
            e.engine_id: e.probe_ratio() for e in live
            if self.gray.state(e.engine_id) == "probation"
        }
        obs = ratio_observations(
            {e.engine_id: e.drain_ratios() for e in live},
            self._gray_windows, self._gray_window_ticks, probes=probes,
        )
        transitions = self.gray.tick(obs)
        # Replan only on transitions that change the planner's PRICING
        # (into/out of probation, or ejection): healthy<->suspect leaves
        # every capacity factor at 1.0, so a replan would re-pack the
        # identical inputs and emit audit noise.
        repricing = [t for t in transitions
                     if "probation" in (t["from"], t["to"])
                     or t["to"] == "ejected"]
        if not repricing:
            return False
        for t in repricing:
            if t["to"] == "ejected":
                self._gray_ejected.add(t["replica"])
                for e in self.engines:
                    if e.engine_id == t["replica"]:
                        e.assign(NodePlan())  # idle the reclaimed chip
        self.rebalance(trigger="gray")
        return True

    def _fidelity_price(self, model: str) -> Optional[Dict[str, float]]:
        """The cost model's BELIEF about one request's engine.step cost:
        the profile row for the model's placement in the CURRENT plan —
        jitter-free, degradation-blind (that blindness is the signal the
        fidelity monitor exists to measure). Prices ONLY engine.step:
        queue.wait is emergent from load, not priced by the profile
        tables, so it must land in ``ungraded`` — a mispriced engine
        can never defame the queue, and vice versa. None when the model
        is not placed (unpriced, counted — never silently graded)."""
        for node_plan in self._current_plan:
            for p in node_plan.placements:
                if p.session.model != model:
                    continue
                prof = self.packer.profiles.get(model)
                row = None
                if prof is not None:
                    row = (prof.row_for(p.batch_size, p.session.seq_len,
                                        p.session.mesh_shape,
                                        p.session.spec)
                           or prof.bucket_for(p.batch_size,
                                              p.session.seq_len,
                                              p.session.mesh_shape,
                                              p.session.spec))
                ms = p.latency_ms if row is None else row.latency_ms
                return {"engine.step": float(ms)}
        return None

    def _on_monitor(self) -> None:
        # Horizon check at FIRE time, not re-arm time: a tick armed just
        # before duration_s would otherwise land in the drain phase and
        # replan on decaying rates — live runs stop their monitor at the
        # workload's end, and with a dead engine such a drain replan can
        # truncate a model off the shrunken cluster and strand its queue.
        if self.clock.now_ms() >= self._monitor_until_ms:
            return
        if self.admission is not None:
            # Same congestion signals the live controller feeds the
            # governor: queue-fill fraction + recent SLO compliance.
            for name, q in self.queues.queues().items():
                self.admission.observe(
                    name, len(q) / max(1, q.max_len), q.slo_compliance()
                )
        if self._retry_policy is not None:
            self._sample_attainment()
        healed = self.check_engine_health()
        grayed = self.check_gray_health()
        changed = self.rates.changed_models(
            self.rate_threshold, self.rate_decrease_multiplier,
            min_span_s=self.rate_min_span_s,
        )
        if changed and not healed and not grayed:  # those already replanned
            self.rebalance(trigger="rate_change")
        if self.observatory is not None:
            # One observatory tick per monitor tick — cumulative class
            # counters + the live hop sketches, same signals the serve
            # controller feeds it (shared classes, shared diet).
            self.observatory.tick(
                {name: q.class_stats()
                 for name, q in self.queues.queues().items()},
                self.rates,
                {name: dict(q.hop_sketches)
                 for name, q in self.queues.queues().items()},
            )
        self.loop.schedule_in(
            max(self.monitoring_interval_s * 1000.0, 1.0),
            self._on_monitor,
        )

    def _sample_attainment(self) -> None:
        """One monitor-tick sample of WINDOWED weighted attainment per
        model (counter deltas since the previous tick, priced by the
        shared :func:`weighted_attainment`) — the recovery timeline the
        metastability pin reads: did attainment return to its pre-fault
        level within the horizon, or did retries keep it pinned down?
        Also mirrors the live controller's congested push: the
        governor's verdict zeroes the model's retry budget."""
        counted = ("completed", "violations", "stale", "dropped",
                   "enqueued")
        sample: Dict[str, Dict[str, float]] = {}
        for name in sorted(self.queues.queues()):
            q = self.queues.queues()[name]
            if self.admission is not None:
                self._retry_budget(name).set_congested(
                    self.admission.congested(name)
                )
            cur = q.class_stats()
            prev = self._attainment_prev.get(name, {})
            delta = {
                cls: {k: c.get(k, 0.0) - prev.get(cls, {}).get(k, 0.0)
                      for k in counted}
                for cls, c in cur.items()
            }
            self._attainment_prev[name] = {
                cls: {k: c.get(k, 0.0) for k in counted}
                for cls, c in cur.items()
            }
            sample[name] = {
                "weighted_attainment": weighted_attainment(delta),
                "completed": sum(d["completed"] for d in delta.values()),
                "congested": (
                    1.0 if self._retry_budget(name).congested else 0.0
                ),
            }
        self.attainment_timeline.append({
            "t_s": round(self.clock.now_s(), 6),
            "models": sample,
        })

    def retry_report(self) -> Dict:
        """Report block for the retry model (rendered only when the
        scenario enables it — canon stays byte-identical)."""
        return {
            "max_attempts": self.retry_max_attempts,
            "backoff_ms": self.retry_backoff_ms,
            "budgets": {m: b.stats()
                        for m, b in sorted(self.retry_budgets.items())},
            "resubmitted": dict(sorted(self.retry_submitted.items())),
            "resubmitted_classes": {
                m: dict(sorted(c.items()))
                for m, c in sorted(self.retry_submitted_classes.items())
            },
            "denied": dict(sorted(self.retry_denied.items())),
            "exhausted": dict(sorted(self.retry_exhausted.items())),
            "attainment_timeline": list(self.attainment_timeline),
        }

    def poison_report(self) -> Dict:
        """Report block for query-of-death injections (rendered only
        when the scenario injects poison)."""
        return {
            "injected": dict(sorted(self.poison_injected.items())),
            "fenced": dict(sorted(self.poison_fenced.items())),
            "quarantined": sorted(self._poison_quarantined),
            "isolations": list(self.poison_isolations),
            "engines": {
                e.engine_id: {
                    "probes": e.poison_probes,
                    "isolated": e.poison_isolated,
                    "rescues": e.poison_rescues,
                }
                for e in sorted(self.engines, key=lambda e: e.engine_id)
                if e.poison_isolated
            },
        }

    # --- observability (live snapshot shape) ------------------------------
    # snapshot()/schedule_log mirror LiveScheduler's surface on purpose:
    # they are the embedding API for dashboards/tools that render a
    # simulated domain exactly like a live one, not internal plumbing
    # (the report reads the audit ring directly).
    def snapshot(self) -> Dict:
        return {
            "time": self.clock.now_s(),
            "rates_rps": self.rates.rates(),
            "scheduled_rates_rps": self.rates.scheduled_rates(),
            "queues": self.queues.stats(),
            "plan": [n.describe() for n in self._current_plan],
            "engines": [e.describe() for e in self.engines],
            "schedule_changes": self.schedule_changes,
            "audit": self.audit.to_dicts(last=20),
        }
