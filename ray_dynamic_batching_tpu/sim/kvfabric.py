"""Sim twin of the KV page fabric: rolling updates with live migration
vs. drain-evict-requeue (ISSUE 18).

A deterministic virtual-time ledger — not the full ``Simulation``
machinery, because the question this twin answers is narrow and the
answer must be exact: when every replica in a deployment is rolled once
(the rolling-update worst case), what happens to the streams that were
mid-decode on each victim?

- **drain** arm (the pre-fabric baseline): a victim's live streams are
  requeue-ELIGIBLE only before their first token (the PR 4 at-most-once
  pin — a stream that already emitted tokens cannot be replayed without
  re-delivering them). Streams past their first token at roll time are
  DROPPED; prefilling streams replay from scratch (requeued).
- **migrate** arm: every live stream is frozen into a parcel
  (page-rounded KV bytes + the cursor) and couriered to a surviving
  replica, costing a pause of ``parcel_mb x COURIER_MS_PER_MB`` — the
  SAME constant the replanner prices moves with (``scheduler/replan``)
  — after which it resumes exactly where it stopped. Zero drops, zero
  replays, by construction.

Both arms run the identical seeded workload; reports render to sorted
JSON so the soak can assert byte-determinism across runs. No wall
clock, no global RNG (sim-determinism lint applies to this file).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass
from typing import Dict, List

from ray_dynamic_batching_tpu.scheduler.replan import COURIER_MS_PER_MB


@dataclass
class MigrationScenario:
    """One rolling-update workload: ``replicas`` engines each decoding
    ``streams_per_replica`` streams, every replica rolled once at a
    staggered virtual time while its streams are mid-flight."""

    replicas: int = 3
    streams_per_replica: int = 6
    mean_prompt_tokens: int = 384
    mean_new_tokens: int = 96
    page_size: int = 128
    # int8-KV tiny model scale: bytes of k+v (+ scale planes) per cached
    # token across layers — only relative cost matters to the gate.
    kv_bytes_per_token: int = 4096
    decode_ms_per_token: float = 8.0
    prefill_ms_per_token: float = 0.15
    # Virtual time at which replica i rolls: roll_start_ms + i * stagger.
    roll_start_ms: float = 250.0
    roll_stagger_ms: float = 150.0
    seed: int = 0


def _pages(tokens: int, page_size: int) -> int:
    return -(-max(0, tokens) // page_size)


def run_migration_sim(scenario: MigrationScenario, arm: str) -> Dict:
    """One arm over the scenario's seeded workload. ``arm`` is
    ``"drain"`` or ``"migrate"``; returns the ledger report."""
    if arm not in ("drain", "migrate"):
        raise ValueError(f"unknown arm {arm!r} (want drain|migrate)")
    rng = random.Random(scenario.seed)
    streams: List[Dict] = []
    for rep in range(scenario.replicas):
        for s in range(scenario.streams_per_replica):
            prompt = max(8, int(rng.gauss(scenario.mean_prompt_tokens,
                                          scenario.mean_prompt_tokens / 4)))
            new = max(2, int(rng.gauss(scenario.mean_new_tokens,
                                       scenario.mean_new_tokens / 4)))
            streams.append({
                "replica": rep,
                "prompt": prompt,
                "new": new,
                # Staggered arrivals: later streams are mid-prefill or
                # early-decode when their replica rolls.
                "arrival_ms": rng.uniform(0.0, 400.0),
            })

    completed = dropped = requeued = migrations = 0
    tokens_emitted = 0
    parcel_bytes_total = 0
    pauses: List[float] = []
    for st in streams:
        roll_ms = (scenario.roll_start_ms
                   + st["replica"] * scenario.roll_stagger_ms)
        first_tok_ms = (st["arrival_ms"]
                        + st["prompt"] * scenario.prefill_ms_per_token)
        done_ms = first_tok_ms + st["new"] * scenario.decode_ms_per_token
        if done_ms <= roll_ms:
            # Finished before its replica rolled: unaffected either way.
            completed += 1
            tokens_emitted += st["new"]
            continue
        if arm == "drain":
            if roll_ms < first_tok_ms:
                # Still prefilling: no token emitted yet, replayable.
                requeued += 1
                completed += 1
                tokens_emitted += st["new"]
            else:
                # Past first token: the at-most-once pin forbids replay
                # — the drain arm can only shed it.
                dropped += 1
                k = int((roll_ms - first_tok_ms)
                        / scenario.decode_ms_per_token) + 1
                tokens_emitted += min(k, st["new"])
            continue
        # migrate arm: prefilling streams requeue exactly as before (no
        # pages worth shipping beats a cheap replay); live streams ship.
        if roll_ms < first_tok_ms:
            requeued += 1
            completed += 1
            tokens_emitted += st["new"]
            continue
        k = int((roll_ms - first_tok_ms) / scenario.decode_ms_per_token) + 1
        k = min(k, st["new"])
        cache_len = st["prompt"] + k
        nbytes = (_pages(cache_len, scenario.page_size)
                  * scenario.page_size * scenario.kv_bytes_per_token)
        parcel_bytes_total += nbytes
        pauses.append(nbytes / 1e6 * COURIER_MS_PER_MB)
        migrations += 1
        completed += 1
        tokens_emitted += st["new"]

    tokens_expected = 0
    for st in streams:
        if arm == "drain":
            roll_ms = (scenario.roll_start_ms
                       + st["replica"] * scenario.roll_stagger_ms)
            first_tok_ms = (st["arrival_ms"]
                            + st["prompt"] * scenario.prefill_ms_per_token)
            done_ms = (first_tok_ms
                       + st["new"] * scenario.decode_ms_per_token)
            if done_ms > roll_ms and roll_ms >= first_tok_ms:
                # A shed stream's client got only the tokens emitted
                # before the roll.
                k = int((roll_ms - first_tok_ms)
                        / scenario.decode_ms_per_token) + 1
                tokens_expected += min(k, st["new"])
                continue
        tokens_expected += st["new"]

    report = {
        "arm": arm,
        "arrivals": len(streams),
        "completed": completed,
        "dropped": dropped,
        "requeued": requeued,
        "migrations": migrations,
        "parcel_mb_total": round(parcel_bytes_total / 1e6, 3),
        "pause_ms_mean": round(sum(pauses) / len(pauses), 4) if pauses
        else 0.0,
        "pause_ms_max": round(max(pauses), 4) if pauses else 0.0,
        "tokens_emitted": tokens_emitted,
        "tokens_expected": tokens_expected,
        "conserved": (completed + dropped == len(streams)
                      and tokens_emitted == tokens_expected),
    }
    return report


def render_json(report: Dict) -> str:
    """Canonical byte form for determinism comparison (sorted keys,
    fixed separators — same discipline as ``sim/report.render_json``)."""
    return json.dumps(report, indent=2, sort_keys=True)
