"""Virtual clock + discrete-event kernel — the simulator's only time source.

Nexus (SOSP'19) validated its planner in simulation and Clockwork
(OSDI'20) showed predictable per-batch latencies make offline evaluation
faithful; both rest on one primitive: a clock that advances by EVENT, not
by wall time. Everything in ``sim/`` reads time from :class:`VirtualClock`
and yields control through :class:`EventLoop` — ``time.time`` /
``time.sleep`` are lint findings here (the ``sim-determinism`` rule), so
a 10-minute workload replays in milliseconds and two same-seed runs are
byte-identical.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, List, Tuple


class VirtualClock:
    """Current simulated time. Only :class:`EventLoop` advances it."""

    def __init__(self, start_ms: float = 0.0) -> None:
        self._now_ms = float(start_ms)

    def now_ms(self) -> float:
        return self._now_ms

    def now_s(self) -> float:
        """Seconds view — drop-in for the ``clock=`` seams the live stack
        already exposes (``RateRegistry``, ``AuditLog(now=...)``)."""
        return self._now_ms / 1000.0


class EventLoop:
    """Deterministic discrete-event kernel: a heap of (time, seq, fn).

    Ties break on insertion order (``seq``), never on callable identity,
    so a given schedule of events always fires in one canonical order —
    the substrate of the byte-identical-report guarantee.
    """

    def __init__(self, clock: VirtualClock) -> None:
        self.clock = clock
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = itertools.count()

    def schedule_at(self, t_ms: float, fn: Callable[[], None]) -> None:
        """Run ``fn`` at virtual ``t_ms`` (clamped to now — the past is
        immutable in a discrete-event world)."""
        t_ms = max(float(t_ms), self.clock.now_ms())
        heapq.heappush(self._heap, (t_ms, next(self._seq), fn))

    def schedule_in(self, delta_ms: float, fn: Callable[[], None]) -> None:
        self.schedule_at(self.clock.now_ms() + max(0.0, delta_ms), fn)

    def __len__(self) -> int:
        return len(self._heap)

    def run_until(self, t_ms: float) -> int:
        """Fire every event with timestamp <= ``t_ms`` in order, advancing
        the clock to each; returns the number fired. The clock lands on
        ``t_ms`` afterwards even if the heap drained early."""
        fired = 0
        while self._heap and self._heap[0][0] <= t_ms:
            when, _, fn = heapq.heappop(self._heap)
            self.clock._now_ms = when
            fn()
            fired += 1
        self.clock._now_ms = max(self.clock._now_ms, float(t_ms))
        return fired
