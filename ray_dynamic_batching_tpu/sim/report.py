"""Simulation reports — deterministic JSON + the A/B comparison.

The report is the simulator's product: per-model SLO attainment (shed
load counts as missed, same formula as ``tools/run_slo_demo.py``'s
per-phase grading), latency percentiles, per-chip measured occupancy,
drop/stale counts, migration count, and the full audit trail (virtual
timestamps). ``render_json`` is byte-deterministic — sorted keys, floats
rounded at fixed precision — so same-seed runs are ``diff``-clean and CI
can ratchet on exact output.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional


def slo_attainment(counters: Dict[str, float]) -> float:
    """Fraction of accounted requests that met their SLO, counting shed
    load (stale discards + drops) as misses — a dropped request missed
    its SLO as surely as a late completion (run_slo_demo's rule)."""
    accounted = (counters.get("completed", 0.0)
                 + counters.get("stale", 0.0)
                 + counters.get("dropped", 0.0))
    misses = (counters.get("violations", 0.0)
              + counters.get("stale", 0.0)
              + counters.get("dropped", 0.0))
    return 1.0 - misses / accounted if accounted else 1.0


def shed_by_class(model_report: Dict[str, Any]) -> Dict[str, float]:
    """Per-class shed volume (queue sheds: stale + dropped) from one
    model's report entry. Rejected-at-admission is deliberately NOT shed
    — it is its own accounting category (offered = admission_rejected +
    enqueued; enqueued = completed + shed + pending)."""
    out: Dict[str, float] = {}
    for cls, c in (model_report.get("classes") or {}).items():
        out[cls] = float(c.get("stale", 0)) + float(c.get("dropped", 0))
    return out


def shed_fraction(model_report: Dict[str, Any], qos_class: str) -> float:
    """Fraction of the model's total shed volume carried by ``qos_class``
    (1.0 when nothing shed — an empty shed trivially satisfies any
    "class X absorbs the shed" floor)."""
    sheds = shed_by_class(model_report)
    total = sum(sheds.values())
    if total <= 0:
        return 1.0
    return sheds.get(qos_class, 0.0) / total


def merged_hop_sketches(queues) -> Dict[str, Any]:
    """One mergeable sketch per hop across every model's sim queue
    (sketch merge is exact — this is the aggregation the live side's
    ``utils.hops.hop_sketches`` produces, so drift compares align)."""
    from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch

    groups: Dict[str, list] = {}
    for q in queues.queues().values():
        for hop, sk in q.hop_sketches.items():
            groups.setdefault(hop, []).append(sk)
    return {hop: QuantileSketch.merged(sks) for hop, sks in groups.items()}


def hop_drift_report(
    live: Dict[str, Any],
    sim: Dict[str, Any],
    tolerance: float = 0.5,
    quantiles=(0.5, 0.95),
    min_count: int = 5,
) -> Dict[str, Any]:
    """Name the hops where the simulator's cost model diverges from a
    live trace beyond ``tolerance`` (relative, per quantile).

    ``live``/``sim`` map hop -> QuantileSketch (or anything exposing
    ``quantile``/``count``). Only hops observed on BOTH sides with at
    least ``min_count`` samples are graded — a hop the sim cannot
    express (proxy/handle/router) is listed under ``ungraded``, never
    silently skipped. PR 3's parity pin said "the sim agrees in
    aggregate"; this says WHICH hop's pricing drifted when it stops
    agreeing."""
    graded: Dict[str, Any] = {}
    drifting = []
    ungraded = {}
    for hop in sorted(set(live) | set(sim)):
        a, b = live.get(hop), sim.get(hop)
        if a is None or b is None or min(a.count, b.count) < min_count:
            ungraded[hop] = {
                "live_count": 0 if a is None else a.count,
                "sim_count": 0 if b is None else b.count,
            }
            continue
        entry: Dict[str, Any] = {"live_count": a.count, "sim_count": b.count}
        worst = 0.0
        for q in quantiles:
            lv, sv = a.quantile(q), b.quantile(q)
            denom = max(abs(lv), 1e-9)
            drift = abs(sv - lv) / denom
            entry[f"p{round(q * 100):d}"] = {
                "live_ms": lv, "sim_ms": sv, "drift": drift,
            }
            worst = max(worst, drift)
        entry["worst_drift"] = worst
        entry["ok"] = worst <= tolerance
        if not entry["ok"]:
            drifting.append(hop)
        graded[hop] = entry
    return {
        "metric": "hop_drift",
        "tolerance": tolerance,
        "hops": graded,
        "ungraded": ungraded,
        "drifting_hops": drifting,
        "ok": not drifting,
    }


def gray_timeline(report: Dict[str, Any]) -> Dict[str, list]:
    """Per-replica gray_state timeline from one report: engine id ->
    ordered ``[{at, from, to, p50_ms, p95_ms}, ...]``. Empty when the
    scenario ran without gray detection. The straggler soak reads this
    to grade detection latency (degradation onset -> first probation
    entry) and the reclaim edge (heal -> back to healthy)."""
    gray = report.get("gray") or {}
    out: Dict[str, list] = {}
    for t in gray.get("timeline", []):
        out.setdefault(t["replica"], []).append(
            {k: t[k] for k in ("at", "from", "to", "p50_ms", "p95_ms")
             if k in t}
        )
    return out


def format_gray_timeline(report: Dict[str, Any]) -> str:
    """Terminal block for the per-replica gray_state timeline."""
    timeline = gray_timeline(report)
    if not timeline:
        return "gray: detection disabled or no transitions"
    lines = [f"{'replica':<10} {'t(s)':>8}  transition"]
    for rid in sorted(timeline):
        for t in timeline[rid]:
            lines.append(
                f"{rid:<10} {t['at']:>8.2f}  {t['from']} -> {t['to']}"
            )
    final = (report.get("gray") or {}).get("final_states", {})
    if final:
        lines.append("final: " + ", ".join(
            f"{rid}={st}" for rid, st in sorted(final.items())
        ))
    return "\n".join(lines)


def alert_timeline(report: Dict[str, Any]) -> Dict[str, list]:
    """Per-(deployment/qos) burn-alert timeline from one report:
    ``"model/qos" -> ordered [{at, from, to, fast_burn, slow_burn},
    ...]``. Empty when the scenario ran without the observatory. The
    observatory soak reads this to pin the overload arm's transition
    sequence (``ok -> warning -> page -> resolved``) — the alert
    analogue of :func:`gray_timeline`."""
    obs = report.get("observatory") or {}
    alerts = obs.get("alerts") or {}
    out: Dict[str, list] = {}
    for t in alerts.get("timeline", []):
        out.setdefault(f"{t['key']}/{t['qos']}", []).append(
            {k: t[k] for k in ("at", "from", "to", "fast_burn",
                               "slow_burn") if k in t}
        )
    return out


def format_alert_timeline(report: Dict[str, Any]) -> str:
    """Terminal block for the burn-alert timeline."""
    timeline = alert_timeline(report)
    if not timeline:
        return "alerts: observatory disabled or no transitions"
    lines = [f"{'deployment/qos':<20} {'t(s)':>8}  transition"]
    for key in sorted(timeline):
        for t in timeline[key]:
            lines.append(
                f"{key:<20} {t['at']:>8.2f}  {t['from']} -> {t['to']}"
                f"  (fast={t.get('fast_burn')} slow={t.get('slow_burn')})"
            )
    final = ((report.get("observatory") or {}).get("alerts") or {}).get(
        "final_states", {}
    )
    if final:
        lines.append("final: " + ", ".join(
            f"{key}/{qos}={st}"
            for key, per_qos in sorted(final.items())
            for qos, st in sorted(per_qos.items())
        ))
    return "\n".join(lines)


def format_partition_story(report: Dict[str, Any]) -> str:
    """Terminal block for one partition-sim arm (sim/frontdoor.
    run_partition_sim): the leadership story, the replay cost, the
    over-admission vs its fail-closed bound, and per-shard ledger
    degradation — the human-readable face of what the soak gate pins."""
    st = report.get("store", {})
    lines = [
        f"partition[{report.get('scenario', {}).get('name', '?')}] "
        f"leader={st.get('leader')} epoch={st.get('epoch')} "
        f"self_demotions={st.get('self_demotions')} "
        f"split_brain_commits={st.get('split_brain_commits')} "
        f"fence_rejections={st.get('rejected_appends')}",
        f"  log: appended_total={st.get('appended_total')} "
        f"tail={st.get('log_tail_records')} "
        f"max_tail_replayed={st.get('max_tail_replayed')} "
        f"snapshots={st.get('snapshots_taken')}",
        f"  budget: max_over_admitted={report.get('max_over_admitted')} "
        f"bound={report.get('degrade_bound')} "
        f"reconverged={report.get('reconverged')}",
    ]
    for fo in st.get("failovers", []):
        lines.append(
            f"  failover @{fo['at_s']}s -> {fo['owner']} "
            f"epoch {fo['epoch']} (snapshot_index={fo['snapshot_index']}, "
            f"tail_replayed={fo['tail_replayed']})"
        )
    for sid, lg in sorted((report.get("ledgers") or {}).items()):
        if lg.get("degraded_entries"):
            lines.append(
                f"  ledger {sid}: degraded {lg['degraded_entries']}x, "
                f"merged={lg['merged']} stale_at_end={lg['stale_at_end']}"
            )
    return "\n".join(lines)


def _round(value: Any, nd: int = 4) -> Any:
    if isinstance(value, float):
        return round(value, nd)
    if isinstance(value, dict):
        return {k: _round(v, nd) for k, v in value.items()}
    if isinstance(value, list):
        return [_round(v, nd) for v in value]
    return value


def render_json(report: Dict[str, Any]) -> str:
    """Canonical bytes: sorted keys, fixed float precision, newline-
    terminated. Two same-seed runs must produce IDENTICAL output."""
    return json.dumps(_round(report), sort_keys=True, indent=2) + "\n"


def compare_reports(a: Dict[str, Any], b: Dict[str, Any],
                    label_a: str = "A", label_b: str = "B") -> Dict[str, Any]:
    """The A/B harness: per-model attainment/p99 deltas, chip usage,
    migrations — the decision surface for "can we drop a chip?" /
    "would plan B hold the SLOs?"."""
    models = sorted(set(a.get("models", {})) | set(b.get("models", {})))
    per_model = {}
    for m in models:
        am = a.get("models", {}).get(m, {})
        bm = b.get("models", {}).get(m, {})
        per_model[m] = {
            "slo_attainment": {
                label_a: am.get("slo_attainment"),
                label_b: bm.get("slo_attainment"),
                "delta": (
                    None
                    if m not in a.get("models", {})
                    or m not in b.get("models", {})
                    else round(bm["slo_attainment"] - am["slo_attainment"], 4)
                ),
            },
            "latency_p99_ms": {
                label_a: am.get("latency_p99_ms"),
                label_b: bm.get("latency_p99_ms"),
            },
            "shed": {
                label_a: (am.get("dropped", 0) + am.get("stale", 0)),
                label_b: (bm.get("dropped", 0) + bm.get("stale", 0)),
            },
        }
    worst_a = min(
        (m.get("slo_attainment", 1.0) for m in a.get("models", {}).values()),
        default=1.0,
    )
    worst_b = min(
        (m.get("slo_attainment", 1.0) for m in b.get("models", {}).values()),
        default=1.0,
    )
    return {
        "labels": [label_a, label_b],
        "models": per_model,
        "chips_used": {label_a: a.get("chips_used"),
                       label_b: b.get("chips_used")},
        "schedule_changes": {label_a: a.get("schedule_changes"),
                             label_b: b.get("schedule_changes")},
        "worst_slo_attainment": {label_a: round(worst_a, 4),
                                 label_b: round(worst_b, 4)},
        "winner": (label_a if worst_a > worst_b
                   else label_b if worst_b > worst_a else "tie"),
    }


def format_compare(diff: Dict[str, Any]) -> str:
    """Terminal table for the A/B diff."""
    la, lb = diff["labels"]
    lines = [
        f"{'model':<20} {'attain ' + la:>12} {'attain ' + lb:>12} "
        f"{'delta':>8} {'p99 ' + la:>10} {'p99 ' + lb:>10}",
    ]
    for m, row in sorted(diff["models"].items()):
        att = row["slo_attainment"]
        p99 = row["latency_p99_ms"]

        def fmt(v: Optional[float], nd: int = 4) -> str:
            return "-" if v is None else f"{v:.{nd}f}"

        lines.append(
            f"{m:<20} {fmt(att[la]):>12} {fmt(att[lb]):>12} "
            f"{fmt(att['delta']):>8} {fmt(p99[la], 1):>10} "
            f"{fmt(p99[lb], 1):>10}"
        )
    lines.append(
        f"chips: {la}={diff['chips_used'][la]} {lb}={diff['chips_used'][lb]}"
        f"  schedule_changes: {la}={diff['schedule_changes'][la]} "
        f"{lb}={diff['schedule_changes'][lb]}  worst attainment: "
        f"{la}={diff['worst_slo_attainment'][la]:.4f} "
        f"{lb}={diff['worst_slo_attainment'][lb]:.4f}"
        f"  winner: {diff['winner']}"
    )
    return "\n".join(lines)
