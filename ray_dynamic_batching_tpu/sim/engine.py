"""Simulated duty-cycle engine — ``engine/worker.py`` advanced by events.

One :class:`SimEngine` is one chip. It re-enacts ``ReplicaEngine``'s hot
loop against the virtual clock, with the committed profile tables as the
execution cost model (Clockwork's premise: per-batch latency on static
XLA buckets is predictable, so the table row IS the step):

live ``ReplicaEngine``                  | here
----------------------------------------|----------------------------------
``assign()`` queues a plan; swap lands  | ``assign()`` stores a pending
at a cycle boundary after off-thread    | plan; swapped at the next
prepare                                 | slice-0 event (prepare is
                                        | off-path live, so it costs the
                                        | simulated timeline nothing)
``_run_placement``: pop batch (fixed    | same pop against the sim queue
size, staleness discard at profiled     | (same staleness rule), then the
latency), run the compiled step         | step "runs" by advancing virtual
                                        | time by the profile row latency
slice sleep: co-tenant gets its         | slice advance =
``occupancy * duty`` share              | max(step_ms, occupancy * duty)
leftover duty-cycle absorption          | cycle end = max(cycle_start +
                                        | duty, last slice end)
idle engine sleeps ``idle_wait_s``      | idle event re-armed at
                                        | ``idle_wait_ms``

Each placement's slice is its OWN event (not one synchronous cycle), so
arrivals that land mid-cycle are visible to later slices exactly as they
are to the live pop at wall time.

Step latency uses the row's MEAN (what a live run actually measures per
step); optional seeded gaussian jitter (``latency_std_ms``) stays
deterministic. The planner's occupancy math keeps using worst-case —
that asymmetry is the live system's too.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ray_dynamic_batching_tpu.profiles.table import (
    BatchProfile,
    expected_tokens_per_round,
)
from ray_dynamic_batching_tpu.scheduler.nexus import NodePlan, Placement
from ray_dynamic_batching_tpu.sim.clock import EventLoop, VirtualClock
from ray_dynamic_batching_tpu.sim.queue import SimQueueManager


class SimEngine:
    """One simulated chip's duty-cycle executor."""

    def __init__(
        self,
        engine_id: str,
        queues: SimQueueManager,
        profiles: Dict[str, BatchProfile],
        loop: EventLoop,
        clock: VirtualClock,
        idle_wait_ms: float = 10.0,
        jitter_rng: Optional[random.Random] = None,
        occupancy_model: str = "batch",
        occupancy_floor: float = 0.35,
        width: int = 1,
        chip_ids: Optional[List[str]] = None,
        spec_rates: Optional[Dict[str, float]] = None,
        prefill_mode: str = "mono",
        prefill_chunk_ms: float = 0.0,
        prefill_chunks_per_turn: int = 1,
    ) -> None:
        if occupancy_model not in ("batch", "slot"):
            raise ValueError(
                f"unknown occupancy_model {occupancy_model!r} "
                "(want 'batch' or 'slot')"
            )
        if prefill_mode not in ("mono", "chunked"):
            raise ValueError(
                f"unknown prefill_mode {prefill_mode!r} "
                "(want 'mono' or 'chunked')"
            )
        self.engine_id = engine_id
        self.queues = queues
        self.profiles = profiles
        self.loop = loop
        self.clock = clock
        self.idle_wait_ms = idle_wait_ms
        self.jitter_rng = jitter_rng  # None = exact mean latencies
        # Mesh slice (ROADMAP item 2): one SimEngine is one SCHEDULABLE
        # UNIT — a single chip (width 1, the classic domain) or a
        # gang-scheduled TP slice of ``width`` chips. A slice executes
        # node plans priced from its mesh profile rows; a single dead
        # chip fails the WHOLE slice (``fail_chip`` — the sim twin of
        # serve/failover.SliceDeadError), and the scheduler re-forms the
        # survivors into narrower slices at the heal tick.
        self.width = max(1, int(width))
        self.chip_ids = list(chip_ids) if chip_ids else [engine_id]
        self.dead_chips: set = set()
        self.failed_chip: Optional[int] = None
        # Decode cost model (ISSUE 7): "batch" prices every pop at the
        # profile row regardless of fill — the slab/shape-bucketed story,
        # where a 3-request pop in a 16-slot bucket pays the full step.
        # "slot" prices a partially-full pop at
        #   row_latency * (floor + (1 - floor) * fill)
        # — the paged/continuous-batching story: the floor is the
        # fill-invariant share (the weight stream a decode step pays no
        # matter how many slots are live), the proportional part the
        # per-slot KV traffic. Occupancy is ACCOUNTED in both modes (the
        # report's slot_occupancy) so slab-vs-paged what-ifs compare it.
        self.occupancy_model = occupancy_model
        self.occupancy_floor = float(occupancy_floor)
        # Speculative cost model (ISSUE 13): model -> LIVE draft-token
        # acceptance rate, a dict SHARED across the cluster's engines
        # and mutated by AcceptanceCollapse scenario events — the sim's
        # ground truth, which may diverge from the PROFILED rate the
        # planner priced with (that divergence is exactly what the
        # acceptance-collapse chaos arm measures). A spec placement's
        # step cost is its spec row's per-ROUND latency divided by
        # expected_tokens_per_round(live_rate, k); absent from the dict,
        # the session's planned rate applies.
        self.spec_rates: Dict[str, float] = (
            spec_rates if spec_rates is not None else {}
        )
        # Prefill interleave model (ISSUE 15): long-prompt requests
        # carry ``prefill_ms`` of prefill cost BEYOND the profile row.
        # "mono" executes it inside the popped turn (the whole train
        # stalls the slice — head-of-line blocking, the legacy
        # admission). "chunked" enqueues it on a FIFO chunk backlog the
        # engine drains between cycles at ``prefill_chunk_ms x
        # prefill_chunks_per_turn`` per cycle — the virtual-clock twin
        # of the engine's token-budget scheduler: decode turns advance
        # every cycle, and a long request completes when its last chunk
        # event lands.
        self.prefill_mode = prefill_mode
        self.prefill_chunk_ms = float(prefill_chunk_ms)
        self.prefill_chunks_per_turn = max(1, int(prefill_chunks_per_turn))
        self._prefill_backlog: List[list] = []  # [queue, request, remaining]
        self._plan = NodePlan()
        self._pending: Optional[NodePlan] = None
        self._cycle_start_ms = 0.0
        self._started = False
        # Scenario failure injection: a dead engine stops popping work at
        # its next event (queued requests live in the SHARED per-model
        # queues, so they wait for the heal replan, exactly as live).
        self.alive = True
        self.failed_at_ms: Optional[float] = None
        # Gray degradation (EngineDegradation): step latency multiplies
        # by slow_factor and gains stall_ms of dead air — the sim twin of
        # the live chaos slowdown modes. The engine stays "healthy()":
        # gray failures are exactly the ones liveness checks miss.
        self.slow_factor = 1.0
        self.stall_ms = 0.0
        self.degraded_at_ms: Optional[float] = None
        # Observed/expected step-latency ratios for the LAST executed
        # batches (model-agnostic: a healthy engine reads ~1.0 whatever
        # it hosts, a 10x straggler reads ~10). The gray monitor's sim
        # observations come from here; drained per monitor tick so a
        # heal is visible the tick after it happens. Armed only when a
        # scenario enables gray monitoring (no silent growth otherwise).
        self.track_ratios = False
        self._fresh_ratios: list = []
        # Last pre-degradation step cost: the synthetic probation
        # probe's baseline (an idled probationed engine executes no
        # batches, so it remembers what a step SHOULD cost).
        self._last_expected_ms = 10.0
        # --- accounting ---
        self.busy_ms = 0.0
        self.batches = 0
        self.requests = 0
        self.cycle_count = 0
        self.swap_count = 0
        # Slot-occupancy accounting: filled vs offered slots over every
        # EXECUTED batch (empty pops don't count — an idle engine is not
        # a half-empty one).
        self.slots_filled = 0
        self.slots_offered = 0
        # Query-of-death accounting (ISSUE 19): a popped batch holding a
        # poison request fails, and the engine pays ceil(log2(B)) full
        # bisection probes plus one rescue pass to isolate it (the live
        # replica's _bisect_poison cost model). The scheduler's
        # on_poison hook quarantines the condemned id cluster-wide.
        self.on_poison = None
        self.poison_probes = 0
        self.poison_rescues = 0
        self.poison_isolated = 0

    # --- scheduler-facing surface (duck-matches ReplicaEngine) -----------
    @property
    def models(self) -> List[str]:
        return [p.session.model for p in self._plan.placements]

    def assign(self, plan: NodePlan) -> None:
        """Queue a new node plan; applied at the next cycle boundary
        (live: background prepare, pointer swap at cycle boundary)."""
        self._pending = plan

    def healthy(self) -> bool:
        """Same liveness surface the live schedulers consult
        (``ReplicaEngine.healthy`` / test fakes)."""
        return self.alive

    @property
    def mesh_shape(self) -> str:
        """The slice's mesh-shape string (the planner's width key)."""
        return f"1x{self.width}"

    def fail(self) -> None:
        """Kill this engine at the current virtual time (a ``Scenario``
        failure event): every already-scheduled cycle/slice event becomes
        a no-op, so the engine executes nothing past this instant. The
        scheduler's monitor detects the death at its next tick — the same
        detection lag a live control loop pays."""
        if self.alive:
            self.alive = False
            self.failed_at_ms = self.clock.now_ms()

    def fail_chip(self, chip: int) -> None:
        """One chip of the slice dies -> the WHOLE slice fails (its
        compiled programs gang-schedule every chip; losing one loses the
        collective — the SliceDeadError semantics). The surviving chips
        stay healthy silicon: ``surviving_chips`` hands them to the
        scheduler's re-form pass at the heal tick."""
        if not 0 <= chip < self.width:
            raise ValueError(
                f"{self.engine_id}: chip {chip} out of range for a "
                f"width-{self.width} slice"
            )
        # Record the dead chip UNCONDITIONALLY: a second chip of an
        # already-dead slice (correlated rack event) must not be handed
        # back to _reform_slices as healthy silicon. Only the
        # slice-kill itself is once-only (fail() guards).
        self.dead_chips.add(int(chip))
        if self.failed_chip is None:
            self.failed_chip = int(chip)
        self.fail()

    def surviving_chips(self) -> List[str]:
        """Chip ids of this (dead) slice that are still good silicon."""
        return [
            c for i, c in enumerate(self.chip_ids)
            if i not in self.dead_chips
        ]

    def degrade(self, factor: float = 1.0, stall_ms: float = 0.0) -> None:
        """Apply a gray degradation (an ``EngineDegradation`` event):
        every later step costs ``factor x`` its profiled latency plus
        ``stall_ms`` of dead air. ``healthy()`` keeps answering True —
        detection is the gray monitor's job, not liveness's."""
        self.slow_factor = float(factor)
        self.stall_ms = float(stall_ms)
        self.degraded_at_ms = self.clock.now_ms()

    def heal_degradation(self) -> None:
        """The chip recovers (thermal event over): later steps cost the
        profile row again; the gray monitor sees ratios normalize."""
        self.slow_factor = 1.0
        self.stall_ms = 0.0

    @property
    def degraded(self) -> bool:
        return self.slow_factor != 1.0 or self.stall_ms != 0.0

    def drain_ratios(self) -> list:
        """Observed/expected step ratios since the last drain (the gray
        monitor's per-tick observation window)."""
        out, self._fresh_ratios = self._fresh_ratios, []
        return out

    def probe_ratio(self) -> float:
        """One synthetic probation probe: the observed/expected ratio a
        step would score under the CURRENT degradation, stall included —
        based on the last expected step cost so a stall-only straggler
        (factor 1.0, stall_ms > 0) still grades as an outlier instead of
        being prematurely readmitted. 1.0 once healed."""
        base = max(self._last_expected_ms, 1e-9)
        return (base * self.slow_factor + self.stall_ms) / base

    def describe(self) -> str:
        return (
            f"SimEngine({self.engine_id}, "
            f"duty={self._plan.duty_cycle_ms:.1f}ms, "
            f"models={sorted(self.models)})"
        )

    # --- event-driven hot loop -------------------------------------------
    def start(self) -> None:
        if not self._started:
            self._started = True
            self.loop.schedule_at(self.clock.now_ms(), self._on_cycle_start)

    def _step_latency_ms(self, p: Placement) -> float:
        """The cost model: the profile row for the placement's compiled
        bucket. Falls back to the placement's planned latency when the
        table lacks the row (the planner sized it from SOME row).

        Spec placements (session.spec == "on") execute at the spec
        row's per-ROUND latency divided by the expected tokens per
        round at the LIVE acceptance rate (``spec_rates`` — collapse
        events move it out from under the planner's profiled belief).
        Same ``expected_tokens_per_round`` formula the packer priced
        with: only the RATE can diverge, never the model."""
        prof = self.profiles.get(p.session.model)
        row = None
        if prof is not None:
            # Keyed by the session's mesh shape: a TP placement's cost
            # comes from its own slice rows (a default "1x1" lookup
            # would miss them and flatten every TP step to planned
            # worst-case latency, jitter-free).
            mesh = p.session.mesh_shape
            spec = p.session.spec
            row = (prof.row_for(p.batch_size, p.session.seq_len, mesh,
                                spec)
                   or prof.bucket_for(p.batch_size, p.session.seq_len,
                                      mesh, spec))
        if row is None:
            return p.latency_ms
        mean = row.latency_ms
        if self.jitter_rng is not None and row.latency_std_ms > 0:
            mean = max(
                0.1 * mean,
                self.jitter_rng.gauss(mean, row.latency_std_ms),
            )
        if p.session.spec == "on" and row.spec == "on":
            rate = self.spec_rates.get(
                p.session.model, p.session.spec_acceptance
            )
            mean = mean / expected_tokens_per_round(
                rate, p.session.spec_tokens
            )
        return mean

    def _drain_prefill_backlog(self) -> float:
        """Spend up to one cycle's chunk budget advancing the FIFO
        prefill backlog; requests whose last chunk lands complete at
        that virtual instant. Returns the virtual time spent (0.0 with
        an empty backlog — the pre-interleave timeline, bit for bit)."""
        if not self._prefill_backlog:
            return 0.0
        # Deadline economics FIRST: a train whose owner is already past
        # its deadline is shed like the queue's own stale discard (the
        # live engine never admits it — the queue stales it before a
        # slot frees) — never silently retained, never a drop.
        now = self.clock.now_ms()
        keep = []
        for entry in self._prefill_backlog:
            if entry[1].deadline_ms < now:
                entry[0].count_backlog_stale(entry[1])
            else:
                keep.append(entry)
        self._prefill_backlog = keep
        quantum = self.prefill_chunk_ms * self.prefill_chunks_per_turn
        spent = 0.0
        while self._prefill_backlog and spent < quantum - 1e-9:
            entry = self._prefill_backlog[0]
            step = min(entry[2], quantum - spent)
            entry[2] -= step
            spent += step
            if entry[2] <= 1e-9:
                self._prefill_backlog.pop(0)
                entry[0].record_batch_completion([entry[1]], now + spent)
        self.busy_ms += spent
        return spent

    def flush_prefill_backlog(self) -> int:
        """End-of-run shed: trains still holding chunks when the
        simulation horizon closes are discarded as stale (the live
        drain's abort path) so accounting conserves exactly. Returns
        the count."""
        n = len(self._prefill_backlog)
        for queue, req, _remaining in self._prefill_backlog:
            queue.count_backlog_stale(req)
        self._prefill_backlog = []
        return n

    def _on_cycle_start(self) -> None:
        if not self.alive:
            return
        if self._pending is not None:
            self._plan = self._pending
            self._pending = None
            self.swap_count += 1
        # Budgeted chunk work rides the cycle boundary: at most one
        # quantum between decode turns — the engine-side stall bound.
        spent = self._drain_prefill_backlog()
        if not self._plan.placements:
            self.loop.schedule_in(
                max(self.idle_wait_ms, spent), self._on_cycle_start
            )
            return
        self._cycle_start_ms = self.clock.now_ms()
        if spent > 0.0:
            self.loop.schedule_in(spent, lambda: self._on_slice(0))
        else:
            self._on_slice(0)

    def _on_slice(self, idx: int) -> None:
        if not self.alive:
            return
        plan = self._plan
        if idx >= len(plan.placements):  # plan shrank under us: new cycle
            self._end_cycle()
            return
        p = plan.placements[idx]
        queue = self.queues.queue(p.session.model)
        # Live NexusFixedBatch: fixed scheduled size, never waits, stale
        # discard priced at the placement's (worst-case) latency.
        batch = queue.get_batch(
            p.batch_size, expected_latency_ms=p.latency_ms
        )
        exec_ms = 0.0
        if batch:
            exec_ms = self._step_latency_ms(p)
            fill = len(batch) / max(1, p.batch_size)
            if self.occupancy_model == "slot":
                # Continuous/paged pricing: a partially-full decode turn
                # costs its fill-scaled share above the fixed floor —
                # the batch-formation stall's cost (full-step pricing of
                # near-empty batches) disappears.
                exec_ms *= (
                    self.occupancy_floor
                    + (1.0 - self.occupancy_floor) * min(1.0, fill)
                )
            if self.degraded or self.track_ratios:
                expected_ms = exec_ms
                self._last_expected_ms = expected_ms
                if self.degraded:
                    # Gray degradation prices on top of everything the
                    # healthy cost model charges (jitter, slot fill):
                    # a 10x straggler is 10x whatever it SHOULD cost.
                    exec_ms = exec_ms * self.slow_factor + self.stall_ms
                if self.track_ratios:
                    self._fresh_ratios.append(
                        exec_ms / max(expected_ms, 1e-9)
                    )
            self.slots_filled += len(batch)
            self.slots_offered += max(1, p.batch_size)
            poisoned = [r for r in batch
                        if getattr(r, "poison_id", None) is not None]
            if poisoned:
                # The step raised: bisect to isolate the query of death.
                # Cost = the failed step + one full re-execution per
                # probe (ceil(log2 B) of them) + one rescue pass for the
                # deferred half — same probe count the live replica's
                # bisection pin asserts. Innocents complete at the
                # delayed instant; the poison is terminally condemned
                # and its id quarantined at the front door.
                probes = (int(math.ceil(math.log2(len(batch))))
                          if len(batch) > 1 else 0)
                rescue = 1 if len(batch) > 1 else 0
                exec_ms += exec_ms * (probes + rescue)
                self.poison_probes += probes
                self.poison_rescues += rescue
                self.poison_isolated += len(poisoned)
                for r in poisoned:
                    queue.count_poisoned(r)
                    if self.on_poison is not None:
                        self.on_poison(r.poison_id, r.model)
                batch = [r for r in batch
                         if getattr(r, "poison_id", None) is None]
            # Long-prompt prefill beyond the profile row (ISSUE 15):
            # mono runs the whole train inside THIS turn (stalling the
            # slice and everything behind it); chunked defers it to the
            # cycle-boundary backlog — those requests complete when
            # their last budgeted chunk event lands, while the rest of
            # the batch completes on time.
            deferred = []
            if self.prefill_mode == "chunked" and self.prefill_chunk_ms > 0.0:
                deferred = [r for r in batch
                            if getattr(r, "prefill_ms", 0.0) > 0.0]
                self._prefill_backlog.extend(
                    [queue, r, r.prefill_ms] for r in deferred
                )
            else:
                exec_ms += sum(getattr(r, "prefill_ms", 0.0)
                               for r in batch)
            done = ([r for r in batch if r not in deferred]
                    if deferred else batch)
            if done:
                queue.record_batch_completion(
                    done, self.clock.now_ms() + exec_ms
                )
            self.busy_ms += exec_ms
            self.batches += 1
            self.requests += len(batch)
        slice_ms = p.occupancy * plan.duty_cycle_ms
        advance_ms = max(exec_ms, slice_ms)
        if idx + 1 < len(plan.placements):
            self.loop.schedule_in(
                advance_ms, lambda: self._on_slice(idx + 1)
            )
        else:
            # Floor the cycle at 0.5 ms of virtual time: a degenerate
            # zero-duty plan must not stall the event loop's clock.
            self.loop.schedule_at(
                max(
                    self._cycle_start_ms + max(plan.duty_cycle_ms, 0.5),
                    self.clock.now_ms() + advance_ms,
                ),
                self._end_cycle,
            )

    def _end_cycle(self) -> None:
        self.cycle_count += 1
        self._on_cycle_start()

    # --- accounting -------------------------------------------------------
    def occupancy(self, elapsed_ms: float) -> float:
        """Measured busy fraction over the run (the live engine's
        ENGINE_OCCUPANCY gauge analogue, but measured not scheduled)."""
        return self.busy_ms / elapsed_ms if elapsed_ms > 0 else 0.0

    def slot_occupancy(self) -> float:
        """Filled fraction of offered batch slots over executed batches
        (the live engine's ACTIVE_SLOTS / num_slots analogue): what share
        of the decode turns' slot capacity carried real work. 1.0 when
        the engine never ran a batch (an idle engine wastes nothing)."""
        if self.slots_offered == 0:
            return 1.0
        return self.slots_filled / self.slots_offered
