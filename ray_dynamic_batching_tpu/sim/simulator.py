"""Scenario + Simulation — one what-if run, end to end.

A :class:`Scenario` is everything a run needs besides the profile
tables: the model contracts (SLO, seq bucket), the traffic (synthetic
``RatePattern`` per model, or an explicit arrival list recorded from a
live run), the cluster size, the control-loop knobs, and the seed.
:class:`Simulation` wires the virtual-clock substrate under the REAL
planner stack and runs the event loop to the horizon:

    profiles -> SquishyBinPacker -> decide_replan     (live planner code)
    RateRegistry(clock=virtual) -> changed_models     (live rate code)
    SimQueueManager / SimEngine                       (live semantics, §sim/)
    AuditLog(now=virtual)                             (live audit ring)

The output is a plain dict; ``sim.report.render_json`` renders it
byte-deterministically. Same profiles + same scenario => same bytes.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_dynamic_batching_tpu.engine.request import (
    DEFAULT_QOS_CLASS,
    DEFAULT_TENANT,
    QOS_RANK,
)
from ray_dynamic_batching_tpu.engine.workload import RatePattern
from ray_dynamic_batching_tpu.profiles.table import BatchProfile
from ray_dynamic_batching_tpu.scheduler.nexus import SquishyBinPacker
from ray_dynamic_batching_tpu.sim.clock import EventLoop, VirtualClock
from ray_dynamic_batching_tpu.sim.control import SimScheduler
from ray_dynamic_batching_tpu.sim.engine import SimEngine
from ray_dynamic_batching_tpu.sim.queue import SimQueueManager
from ray_dynamic_batching_tpu.scheduler.replan import weighted_attainment
from ray_dynamic_batching_tpu.serve.admission import (
    AdmissionController,
    AdmissionPolicy,
)
from ray_dynamic_batching_tpu.sim.report import slo_attainment
from ray_dynamic_batching_tpu.sim.workload import (
    Arrival,
    draw_qos,
    merge_arrivals,
    scale_arrivals,
    synthetic_arrivals,
)

# RatePattern knobs a scenario dict may set (everything but kind/seed).
_PATTERN_FIELDS = (
    "base_rps", "slope", "amplitude", "period_s", "step_at_s",
    "jitter", "spike_at_s", "spike_len_s",
)


# Keys a model entry may carry; anything else is a typo'd knob and a
# silently-defaulted what-if is a confidently wrong one — reject loudly.
_MODEL_KEYS = frozenset(
    ("name", "slo_ms", "seq_len", "rate_rps", "pattern", "poisson",
     "class_mix", "tenant", "mesh_shape", "spec", "spec_acceptance",
     "spec_tokens", "long_frac", "long_prefill_ms")
    + _PATTERN_FIELDS
)

# AdmissionPolicy knobs a scenario's "admission" object may set.
_ADMISSION_KEYS = frozenset(
    ("rate_rps", "burst", "degraded_class_fractions", "depth_high",
     "depth_low", "compliance_low", "compliance_high", "max_tenants",
     "congested_floor", "congested_exit")
)


@dataclass
class SimModelSpec:
    """One model's serving contract + its synthetic traffic shape."""

    name: str
    slo_ms: float
    seq_len: int = 0
    pattern: Optional[RatePattern] = None   # None when arrivals are explicit
    poisson: bool = True
    # QoS traffic mix: class -> fraction of this model's arrivals (empty =
    # everything at the default class). Tagging is seeded per model, so
    # the same scenario always produces the same per-request classes.
    class_mix: Dict[str, float] = None
    tenant: str = DEFAULT_TENANT
    # Preferred serving mesh shape ("1x4" = a 4-chip TP slice priced
    # from the profile table's mesh rows; ROADMAP item 2). "1x1" keeps
    # the classic single-chip contract.
    mesh_shape: str = "1x1"
    # Speculative serving arm (ISSUE 13): spec=True prices and executes
    # this model through its spec profile rows at spec_acceptance (the
    # PROFILED draft-token acceptance rate — what an on-chip capture's
    # rdb_decode_spec_acceptance gauge read). AcceptanceCollapse events
    # move the LIVE rate out from under this belief mid-run.
    spec: bool = False
    spec_acceptance: float = 0.7
    spec_tokens: int = 4
    # Long-prompt mix (ISSUE 15): ``long_frac`` of this model's arrivals
    # carry ``long_prefill_ms`` of prefill cost beyond the profile row
    # (a seeded per-model draw — deterministic, independent of
    # interleaving). How that cost executes is the SCENARIO's
    # ``prefill_mode`` (mono head-of-line vs budgeted chunk events).
    long_frac: float = 0.0
    long_prefill_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.class_mix is None:
            self.class_mix = {}
        unknown = set(self.class_mix) - set(QOS_RANK)
        if unknown:
            raise ValueError(
                f"{self.name}: unknown qos class(es) {sorted(unknown)} in "
                f"class_mix (known: {sorted(QOS_RANK)})"
            )
        if self.class_mix and sum(self.class_mix.values()) <= 0:
            raise ValueError(
                f"{self.name}: class_mix fractions must sum > 0"
            )
        if not 0.0 <= self.long_frac <= 1.0:
            raise ValueError(
                f"{self.name}: long_frac must be in [0, 1]"
            )
        if self.long_frac > 0.0 and self.long_prefill_ms <= 0.0:
            raise ValueError(
                f"{self.name}: long_frac > 0 needs long_prefill_ms > 0"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any], seed: int = 0) -> "SimModelSpec":
        unknown = set(d) - _MODEL_KEYS
        if unknown:
            raise ValueError(
                f"unknown model key(s) {sorted(unknown)} for "
                f"{d.get('name', '<unnamed>')!r}; known: "
                f"{sorted(_MODEL_KEYS)}"
            )
        pattern = None
        if "rate_rps" in d or "pattern" in d:
            kwargs = {k: d[k] for k in _PATTERN_FIELDS if k in d}
            if "rate_rps" in d:
                kwargs["base_rps"] = float(d["rate_rps"])
            pattern = RatePattern(
                kind=d.get("pattern", "constant"), seed=seed, **kwargs
            )
        return cls(
            name=d["name"],
            slo_ms=float(d["slo_ms"]),
            seq_len=int(d.get("seq_len", 0)),
            pattern=pattern,
            poisson=bool(d.get("poisson", True)),
            class_mix={k: float(v)
                       for k, v in dict(d.get("class_mix", {})).items()},
            tenant=str(d.get("tenant", DEFAULT_TENANT)),
            mesh_shape=str(d.get("mesh_shape", "1x1")),
            spec=bool(d.get("spec", False)),
            spec_acceptance=float(d.get("spec_acceptance", 0.7)),
            spec_tokens=int(d.get("spec_tokens", 4)),
            long_frac=float(d.get("long_frac", 0.0)),
            long_prefill_ms=float(d.get("long_prefill_ms", 0.0)),
        )


@dataclass
class EngineFailure:
    """One injected engine death: the engine indexed ``engine`` dies at
    virtual time ``at_s`` (the sim analogue of an injected
    ``replica.loop`` crash / a chaos-killed worker). The scheduler's
    monitor detects it at its next tick and replans over survivors.

    ``chip`` (slice scenarios only) names WHICH chip of a multi-chip
    slice dies: the whole slice fails (SliceDeadError semantics), and
    the surviving chips re-form as narrower slices at the heal tick."""

    at_s: float
    engine: int
    chip: Optional[int] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EngineFailure":
        unknown = set(d) - {"at_s", "engine", "chip"}
        if unknown:
            raise ValueError(
                f"unknown failure key(s) {sorted(unknown)}; "
                "known: ['at_s', 'engine', 'chip']"
            )
        return cls(at_s=float(d["at_s"]), engine=int(d["engine"]),
                   chip=(None if d.get("chip") is None
                         else int(d["chip"])))


@dataclass
class EngineDegradation:
    """One injected GRAY failure: from ``at_s`` the engine runs every
    step at ``factor x`` its profiled latency plus ``stall_ms`` of dead
    air, while still answering ``healthy()`` — the sim twin of the live
    ``RDB_TESTING_SLOWDOWN`` modes (a thermally throttled chip, a slow
    HBM lane). ``heal_at_s`` ends the episode (None = degraded to the
    horizon), so probation-then-reclaim stories are expressible. The
    gray monitor — not liveness — must catch it."""

    at_s: float
    engine: int
    factor: float = 1.0
    stall_ms: float = 0.0
    heal_at_s: Optional[float] = None

    def __post_init__(self) -> None:
        if self.factor < 1.0:
            raise ValueError(
                f"degradation factor must be >= 1, got {self.factor}"
            )
        if self.heal_at_s is not None and self.heal_at_s <= self.at_s:
            raise ValueError(
                f"heal_at_s ({self.heal_at_s}) must be after at_s "
                f"({self.at_s})"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EngineDegradation":
        known = {"at_s", "engine", "factor", "stall_ms", "heal_at_s"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown degradation key(s) {sorted(unknown)}; known: "
                f"{sorted(known)}"
            )
        return cls(
            at_s=float(d["at_s"]),
            engine=int(d["engine"]),
            factor=float(d.get("factor", 1.0)),
            stall_ms=float(d.get("stall_ms", 0.0)),
            heal_at_s=(None if d.get("heal_at_s") is None
                       else float(d["heal_at_s"])),
        )


@dataclass
class AcceptanceCollapse:
    """One injected speculative-acceptance collapse (ISSUE 13 chaos):
    from ``at_s`` the named model's LIVE draft-token acceptance rate
    drops to ``rate`` (adversarial prompts the draft cannot predict —
    the planner keeps pricing at the PROFILED rate), recovering to the
    model's ``spec_acceptance`` at ``heal_at_s`` (None = collapsed to
    the horizon). The gate's claim: throughput degrades to within a
    bounded factor of the non-spec paged arm — a verify round always
    emits >= 1 token — never off a cliff, with zero client-visible
    errors."""

    at_s: float
    model: str
    rate: float = 0.0
    heal_at_s: Optional[float] = None

    def __post_init__(self) -> None:
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(
                f"collapse rate must be in [0, 1], got {self.rate}"
            )
        if self.heal_at_s is not None and self.heal_at_s <= self.at_s:
            raise ValueError(
                f"heal_at_s ({self.heal_at_s}) must be after at_s "
                f"({self.at_s})"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AcceptanceCollapse":
        known = {"at_s", "model", "rate", "heal_at_s"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown collapse key(s) {sorted(unknown)}; known: "
                f"{sorted(known)}"
            )
        return cls(
            at_s=float(d["at_s"]),
            model=str(d["model"]),
            rate=float(d.get("rate", 0.0)),
            heal_at_s=(None if d.get("heal_at_s") is None
                       else float(d["heal_at_s"])),
        )


@dataclass
class PoisonInjection:
    """One injected query of death (ISSUE 19): at ``at_s`` a poison
    request is submitted to ``model`` — any batch executing it fails,
    and the engine pays ceil(log2 B) bisection probes plus a rescue
    pass to isolate it (the live replica's quarantine path, priced at
    virtual time). ``repeat_at_s`` resubmits the SAME poison later: the
    scenario's claim is that the repeat is fenced at the front door
    (quarantine gossip), never poisoning a second batch."""

    at_s: float
    model: str
    poison_id: str = ""
    qos_class: str = DEFAULT_QOS_CLASS
    repeat_at_s: Optional[float] = None

    def __post_init__(self) -> None:
        if self.at_s < 0:
            raise ValueError(f"poison at_s must be >= 0, got {self.at_s}")
        if self.repeat_at_s is not None and self.repeat_at_s <= self.at_s:
            raise ValueError(
                f"repeat_at_s ({self.repeat_at_s}) must be after at_s "
                f"({self.at_s})"
            )
        if self.qos_class not in QOS_RANK:
            raise ValueError(
                f"poison qos_class {self.qos_class!r} unknown "
                f"(known: {sorted(QOS_RANK)})"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PoisonInjection":
        known = {"at_s", "model", "poison_id", "qos_class", "repeat_at_s"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown poison key(s) {sorted(unknown)}; known: "
                f"{sorted(known)}"
            )
        return cls(
            at_s=float(d["at_s"]),
            model=str(d["model"]),
            poison_id=str(d.get("poison_id", "")),
            qos_class=str(d.get("qos_class", DEFAULT_QOS_CLASS)),
            repeat_at_s=(None if d.get("repeat_at_s") is None
                         else float(d["repeat_at_s"])),
        )


# Client-retry model knobs a scenario's "retry" object may set
# (SimScheduler.enable_retries parameters).
_RETRY_KEYS = frozenset(
    ("max_attempts", "backoff_ms", "budget_fraction", "budget_window",
     "min_first_attempts")
)


@dataclass
class Scenario:
    """One simulated deployment under one traffic story."""

    models: List[SimModelSpec]
    duration_s: float = 60.0
    drain_s: float = 5.0
    n_engines: int = 2
    # Slice widths per schedulable unit (ROADMAP item 2): [4, 2, 1, 1]
    # = one 4-chip TP slice, one half-slice, two single chips
    # (len == n_engines). None = the classic all-singles cluster.
    engine_widths: Optional[List[int]] = None
    seed: int = 0
    rate_scale: float = 1.0          # the "at 2x traffic?" knob
    max_queue_len: int = 4096
    monitoring_interval_s: float = 5.0
    rate_threshold: float = 0.05
    rate_decrease_multiplier: float = 2.0
    rate_window_s: float = 10.0
    rate_min_span_s: float = 0.0     # cold-window replan guard (live knob)
    hbm_budget_bytes: int = 12 << 30
    # Planner knobs pinned IN the scenario (not read from ambient
    # RDBConfig): a what-if report must not change because some other
    # code in the process mutated the global config.
    slo_safety_factor: float = 2.2   # live default (ref SLO_hack=2.2)
    slo_compute_fraction: float = 0.5
    hbm_plan_fraction: float = 0.9
    warm_start: bool = True          # initial manual rebalance at t=0
    latency_jitter: bool = False     # seeded gaussian around row means
    # Decode cost model (sim/engine.py): "batch" = slab pricing (every
    # pop costs the full profile row), "slot" = paged/continuous pricing
    # (partially-full turns cost their fill-scaled share above the
    # fill-invariant floor). Slot occupancy is reported in BOTH modes.
    decode_occupancy_model: str = "batch"
    occupancy_floor: float = 0.35
    # Prefill interleave model (ISSUE 15): "mono" executes a long
    # request's prefill inside its popped turn (head-of-line blocking —
    # the legacy admission); "chunked" spends it as
    # ``prefill_chunk_ms x prefill_chunks_per_turn`` virtual-clock
    # chunk events between cycles — the token-budget scheduler's twin.
    # The packer prices chunk-interleaved turns via
    # Session.prefill_chunk_ms when chunked.
    prefill_mode: str = "mono"
    prefill_chunk_ms: float = 0.0
    prefill_chunks_per_turn: int = 1
    # Injected engine deaths (chaos conformance): each kills one sim
    # engine at virtual time t; the monitor heals over survivors.
    failures: List[EngineFailure] = field(default_factory=list)
    # Injected GRAY failures (straggler conformance): slowdowns the gray
    # monitor — not liveness — must catch.
    degradations: List[EngineDegradation] = field(default_factory=list)
    # Injected speculative-acceptance collapses (ISSUE 13 chaos):
    # adversarial traffic drives a model's LIVE acceptance toward 0
    # while the planner keeps its profiled belief.
    spec_collapses: List[AcceptanceCollapse] = field(default_factory=list)
    # Injected queries of death (ISSUE 19): each poisons one batch;
    # bisection isolates it at ceil(log2 B) probe cost and repeats are
    # fenced at the front door.
    poisons: List[PoisonInjection] = field(default_factory=list)
    # Client-retry model knobs (ISSUE 19; SimScheduler.enable_retries
    # parameters). None = no retry loop: canon scenarios stay
    # byte-identical. budget_fraction=None inside the dict models naive
    # unbounded clients — the metastable control arm.
    retry: Optional[Dict[str, Any]] = None

    def retry_config(self) -> Optional[Dict[str, Any]]:
        if self.retry is None:
            return None
        unknown = set(self.retry) - _RETRY_KEYS
        if unknown:
            raise ValueError(
                f"unknown retry key(s) {sorted(unknown)}; known: "
                f"{sorted(_RETRY_KEYS)}"
            )
        return {
            "max_attempts": int(self.retry.get("max_attempts", 3)),
            "backoff_ms": float(self.retry.get("backoff_ms", 50.0)),
            "budget_fraction": (
                None if self.retry.get("budget_fraction") is None
                else float(self.retry["budget_fraction"])
            ),
            "budget_window": int(self.retry.get("budget_window", 512)),
            "min_first_attempts": int(
                self.retry.get("min_first_attempts", 16)
            ),
        }
    # Gray-detection knobs (serve/grayhealth.GrayHealthPolicy fields).
    # None = detection disabled: canon scenarios stay byte-identical.
    gray: Optional[Dict[str, Any]] = None

    def gray_policy(self):
        from ray_dynamic_batching_tpu.serve.grayhealth import (
            GrayHealthPolicy,
        )
        import dataclasses as _dc

        if self.gray is None:
            return None
        known = {f.name for f in _dc.fields(GrayHealthPolicy)}
        unknown = set(self.gray) - known
        if unknown:
            raise ValueError(
                f"unknown gray key(s) {sorted(unknown)}; known: "
                f"{sorted(known)}"
            )
        return GrayHealthPolicy(**self.gray)
    # Token-bucket admission + overload governor, applied per model
    # (serve/admission.AdmissionPolicy knobs; None = admit everything).
    # The LIVE AdmissionController runs here on the virtual clock.
    admission: Optional[Dict[str, Any]] = None
    # SLO observatory knobs (serve/observatory.ObservatoryPolicy fields).
    # None = disabled: canon scenarios stay byte-identical. The LIVE
    # observatory classes run here on the virtual clock.
    observatory: Optional[Dict[str, Any]] = None

    def observatory_policy(self):
        from ray_dynamic_batching_tpu.serve.observatory import (
            ObservatoryPolicy,
        )
        import dataclasses as _dc

        if self.observatory is None:
            return None
        known = {f.name for f in _dc.fields(ObservatoryPolicy)}
        unknown = set(self.observatory) - known
        if unknown:
            raise ValueError(
                f"unknown observatory key(s) {sorted(unknown)}; known: "
                f"{sorted(known)}"
            )
        return ObservatoryPolicy(**self.observatory)
    arrivals: Optional[List[Arrival]] = field(default=None, repr=False)

    def admission_policy(self) -> Optional[AdmissionPolicy]:
        if self.admission is None:
            return None
        unknown = set(self.admission) - _ADMISSION_KEYS
        if unknown:
            raise ValueError(
                f"unknown admission key(s) {sorted(unknown)}; known: "
                f"{sorted(_ADMISSION_KEYS)}"
            )
        kwargs = dict(self.admission)
        if "degraded_class_fractions" in kwargs:
            kwargs["degraded_class_fractions"] = {
                k: float(v)
                for k, v in dict(kwargs["degraded_class_fractions"]).items()
            }
        return AdmissionPolicy(**kwargs)

    # Loader-level keys (profiles/arrivals paths) ride in the same JSON
    # object; everything else must be a real Scenario field.
    _LOADER_KEYS = frozenset({"profiles", "profiles_dir", "arrivals",
                              "_comment"})

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Scenario":
        import dataclasses as _dc

        known = {f.name for f in _dc.fields(cls)} | cls._LOADER_KEYS
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown scenario key(s) {sorted(unknown)}; known: "
                f"{sorted(known - cls._LOADER_KEYS)}"
            )
        seed = int(d.get("seed", 0))
        return cls(
            models=[
                SimModelSpec.from_dict(m, seed=seed + i)
                for i, m in enumerate(d["models"])
            ],
            duration_s=float(d.get("duration_s", 60.0)),
            drain_s=float(d.get("drain_s", 5.0)),
            n_engines=int(d.get("n_engines", 2)),
            engine_widths=(
                None if d.get("engine_widths") is None
                else [int(w) for w in d["engine_widths"]]
            ),
            seed=seed,
            rate_scale=float(d.get("rate_scale", 1.0)),
            max_queue_len=int(d.get("max_queue_len", 4096)),
            monitoring_interval_s=float(d.get("monitoring_interval_s", 5.0)),
            rate_threshold=float(d.get("rate_threshold", 0.05)),
            rate_decrease_multiplier=float(
                d.get("rate_decrease_multiplier", 2.0)
            ),
            rate_window_s=float(d.get("rate_window_s", 10.0)),
            rate_min_span_s=float(d.get("rate_min_span_s", 0.0)),
            hbm_budget_bytes=int(d.get("hbm_budget_bytes", 12 << 30)),
            slo_safety_factor=float(d.get("slo_safety_factor", 2.2)),
            slo_compute_fraction=float(d.get("slo_compute_fraction", 0.5)),
            hbm_plan_fraction=float(d.get("hbm_plan_fraction", 0.9)),
            warm_start=bool(d.get("warm_start", True)),
            latency_jitter=bool(d.get("latency_jitter", False)),
            decode_occupancy_model=str(
                d.get("decode_occupancy_model", "batch")
            ),
            occupancy_floor=float(d.get("occupancy_floor", 0.35)),
            prefill_mode=str(d.get("prefill_mode", "mono")),
            prefill_chunk_ms=float(d.get("prefill_chunk_ms", 0.0)),
            prefill_chunks_per_turn=int(
                d.get("prefill_chunks_per_turn", 1)
            ),
            failures=[
                EngineFailure.from_dict(f) for f in d.get("failures", [])
            ],
            degradations=[
                EngineDegradation.from_dict(g)
                for g in d.get("degradations", [])
            ],
            spec_collapses=[
                AcceptanceCollapse.from_dict(c)
                for c in d.get("spec_collapses", [])
            ],
            poisons=[
                PoisonInjection.from_dict(p) for p in d.get("poisons", [])
            ],
            retry=d.get("retry"),
            gray=d.get("gray"),
            admission=d.get("admission"),
            observatory=d.get("observatory"),
        )


class Simulation:
    """One run of one scenario against one set of profile tables."""

    def __init__(self, profiles: Dict[str, BatchProfile],
                 scenario: Scenario) -> None:
        self.profiles = profiles
        self.scenario = scenario

    # --- workload ---------------------------------------------------------
    def _arrivals(self) -> List[Arrival]:
        sc = self.scenario
        if sc.arrivals is not None:
            return scale_arrivals(sc.arrivals, sc.rate_scale, seed=sc.seed)
        streams = []
        for i, spec in enumerate(sc.models):
            if spec.pattern is None:
                continue
            pattern = spec.pattern
            if sc.rate_scale != 1.0:
                # Synthetic traffic scales at the SOURCE (rate, not trace).
                pattern = RatePattern(
                    kind=pattern.kind,
                    base_rps=pattern.base_rps * sc.rate_scale,
                    slope=pattern.slope * sc.rate_scale,
                    amplitude=pattern.amplitude * sc.rate_scale,
                    period_s=pattern.period_s,
                    step_at_s=pattern.step_at_s,
                    jitter=pattern.jitter,
                    spike_at_s=pattern.spike_at_s,
                    spike_len_s=pattern.spike_len_s,
                    seed=pattern.seed,
                )
            streams.append(
                synthetic_arrivals(
                    spec.name, pattern, sc.duration_s,
                    poisson=spec.poisson, seed=sc.seed * 8191 + i,
                )
            )
        return merge_arrivals(streams)

    def _warm_start_rates(self, arrivals: List[Arrival]) -> Dict[str, float]:
        """The rates the t=0 manual rebalance plans for: the synthetic
        base rates, or (for a recorded trace) the measured rate over the
        first rate window."""
        sc = self.scenario
        if sc.arrivals is None:
            return {
                spec.name: spec.pattern.base_rps * sc.rate_scale
                for spec in sc.models
                if spec.pattern is not None
            }
        span = max(min(sc.rate_window_s, sc.duration_s), 1e-9)
        counts: Dict[str, int] = {}
        for arrival in arrivals:  # plain or class-tagged tuples
            if arrival[0] <= span:
                model = arrival[1]
                counts[model] = counts.get(model, 0) + 1
        return {spec.name: counts.get(spec.name, 0) / span
                for spec in sc.models}

    # --- the run ----------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        sc = self.scenario
        clock = VirtualClock()
        loop = EventLoop(clock)
        queues = SimQueueManager(clock, max_len=sc.max_queue_len)
        jitter_rng = (
            random.Random(sc.seed * 7919 + 13) if sc.latency_jitter else None
        )
        if sc.engine_widths is not None and \
                len(sc.engine_widths) != sc.n_engines:
            raise ValueError(
                f"engine_widths has {len(sc.engine_widths)} entries for "
                f"{sc.n_engines} engines"
            )
        # LIVE speculative acceptance per spec model: seeded from each
        # spec's PROFILED rate, shared by every engine (one dict — the
        # cluster serves one traffic population), mutated by
        # AcceptanceCollapse events at virtual time.
        spec_rates: Dict[str, float] = {
            spec.name: spec.spec_acceptance
            for spec in sc.models if spec.spec
        }
        if sc.prefill_mode == "chunked" and sc.prefill_chunk_ms <= 0.0:
            raise ValueError(
                "prefill_mode='chunked' needs prefill_chunk_ms > 0 — a "
                "zero-cost chunk would silently price as mono"
            )
        engines = []
        chip_base = 0
        for i in range(sc.n_engines):
            width = (sc.engine_widths[i]
                     if sc.engine_widths is not None else 1)
            # Classic clusters keep the historic chip{i} ids (canon);
            # width-typed clusters name units slice{i} over chip ids.
            if sc.engine_widths is None:
                eid, chips = f"chip{i}", None
            else:
                eid = f"slice{i}"
                chips = [f"chip{chip_base + j}" for j in range(width)]
                chip_base += width
            engines.append(
                SimEngine(eid, queues, self.profiles, loop, clock,
                          jitter_rng=jitter_rng,
                          occupancy_model=sc.decode_occupancy_model,
                          occupancy_floor=sc.occupancy_floor,
                          width=width, chip_ids=chips,
                          spec_rates=spec_rates,
                          prefill_mode=sc.prefill_mode,
                          prefill_chunk_ms=sc.prefill_chunk_ms,
                          prefill_chunks_per_turn=(
                              sc.prefill_chunks_per_turn))
            )
        packer = SquishyBinPacker(
            self.profiles, hbm_budget_bytes=sc.hbm_budget_bytes
        )
        # Pin every planner knob from the scenario — the constructor read
        # the ambient config, which is not part of a what-if's inputs.
        packer.hbm_budget = int(sc.hbm_budget_bytes * sc.hbm_plan_fraction)
        packer.slo_safety = sc.slo_safety_factor
        packer.compute_fraction = sc.slo_compute_fraction
        # Slot pricing reaches BOTH halves of the what-if: the planner
        # packs fill-priced turns, and the sim engines execute them at
        # the same fill-scaled cost — plan and timeline stay consistent.
        packer.occupancy_pricing = sc.decode_occupancy_model
        packer.occupancy_floor = sc.occupancy_floor
        sched = SimScheduler(
            packer, engines, queues, loop, clock,
            monitoring_interval_s=sc.monitoring_interval_s,
            rate_threshold=sc.rate_threshold,
            rate_decrease_multiplier=sc.rate_decrease_multiplier,
            rate_window_s=sc.rate_window_s,
            rate_min_span_s=sc.rate_min_span_s,
            gray_policy=sc.gray_policy(),
            observatory_policy=sc.observatory_policy(),
        )
        for spec in sc.models:
            # Chunk-interleaved turns are priced to the planner only
            # when the scenario runs them (one quantum may ride each
            # turn) — mono scenarios register byte-identically.
            chunk_price = (
                sc.prefill_chunk_ms * sc.prefill_chunks_per_turn
                if sc.prefill_mode == "chunked" and spec.long_frac > 0.0
                else 0.0
            )
            sched.register_model(spec.name, slo_ms=spec.slo_ms,
                                 seq_len=spec.seq_len,
                                 mesh_shape=spec.mesh_shape,
                                 spec="on" if spec.spec else "off",
                                 spec_acceptance=spec.spec_acceptance,
                                 spec_tokens=spec.spec_tokens,
                                 prefill_chunk_ms=chunk_price)

        # Admission control at virtual time: the LIVE controller module
        # with the virtual clock injected (deterministic buckets), wired
        # into the scheduler's audit ring so governor transitions land in
        # the same timeline as replans and heals.
        policy = sc.admission_policy()
        if policy is not None:
            admission = AdmissionController(clock=clock.now_s)
            admission.audit = sched.audit
            for spec in sc.models:
                admission.configure(spec.name, policy)
            sched.admission = admission
        queues.audit = sched.audit  # displacement sheds are audited too
        retry_cfg = sc.retry_config()
        if retry_cfg is not None:
            # Stale sheds become budgeted client resubmissions with fresh
            # deadlines — the retry amplification loop the metastability
            # scenarios exercise with budgets on (bounded) and off
            # (naive clients, the control arm).
            sched.enable_retries(**retry_cfg)

        # Only arrivals the horizon will actually fire count as offered
        # load: a recorded trace longer than duration_s is TRUNCATED and
        # says so, and arrivals for models the scenario never registered
        # are IGNORED and say so — both silently inflating 'arrivals'
        # would let capacity conclusions be drawn from a workload the
        # run never carried.
        known = {spec.name for spec in sc.models}
        all_arrivals = self._arrivals()
        arrivals: list = []
        ignored_models: Dict[str, int] = {}
        truncated = 0
        for arrival in all_arrivals:
            t_s, model = arrival[0], arrival[1]
            if model not in known:
                ignored_models[model] = ignored_models.get(model, 0) + 1
            elif t_s >= sc.duration_s:
                truncated += 1
            else:
                arrivals.append(arrival)
        # QoS class tagging: explicit 3-tuple arrivals keep their class;
        # untagged ones draw from the model's class_mix with a per-model
        # seeded stream (deterministic, independent of interleaving).
        specs = {spec.name: spec for spec in sc.models}
        class_rngs = {
            spec.name: random.Random(sc.seed * 4099 + 17 * i)
            for i, spec in enumerate(sc.models)
        }
        # Long-prompt tagging (ISSUE 15): its own per-model seeded
        # stream, drawn ONLY for models with a long mix — canon
        # scenarios consume no RNG state and stay byte-identical.
        long_rngs = {
            spec.name: random.Random(sc.seed * 6007 + 23 * i)
            for i, spec in enumerate(sc.models)
        }

        arrival_counts: Dict[str, int] = {}
        class_offered: Dict[str, Dict[str, int]] = {}
        for arrival in arrivals:
            t_s, model = arrival[0], arrival[1]
            if len(arrival) > 2:
                # Explicitly-tagged trace entry: validate like the live
                # doors do — a typo'd class in a recorded JSONL must not
                # silently serve at beyond-last priority.
                qos = arrival[2]
                if qos not in QOS_RANK:
                    raise ValueError(
                        f"arrival for {model!r} carries unknown qos class "
                        f"{qos!r} (known: {sorted(QOS_RANK)})"
                    )
            else:
                qos = draw_qos(class_rngs[model],
                               specs[model].class_mix)
            arrival_counts[model] = arrival_counts.get(model, 0) + 1
            per_cls = class_offered.setdefault(model, {})
            per_cls[qos] = per_cls.get(qos, 0) + 1
            spec_m = specs[model]
            pre_ms = 0.0
            if (spec_m.long_frac > 0.0
                    and long_rngs[model].random() < spec_m.long_frac):
                pre_ms = spec_m.long_prefill_ms
            loop.schedule_at(
                t_s * 1000.0,
                lambda m=model, q=qos, t=specs[model].tenant,
                pm=pre_ms: (
                    sched.submit(m, qos_class=q, tenant=t, prefill_ms=pm)
                ),
            )

        for i, p in enumerate(sc.poisons):
            if p.model not in known:
                raise ValueError(
                    f"poison names model {p.model!r}, which this scenario "
                    "never registered"
                )
            pid = p.poison_id or f"qod{i}"
            # Injections are offered load like any arrival — conservation
            # (offered == rejected + enqueued) must hold over them too,
            # with the quarantine fence counting as a front-door reject.
            per_cls = class_offered.setdefault(p.model, {})
            n_inj = 1 + (1 if p.repeat_at_s is not None else 0)
            per_cls[p.qos_class] = per_cls.get(p.qos_class, 0) + n_inj
            arrival_counts[p.model] = (
                arrival_counts.get(p.model, 0) + n_inj
            )
            loop.schedule_at(
                p.at_s * 1000.0,
                lambda m=p.model, q=p.qos_class, t=specs[p.model].tenant,
                pid=pid: sched.submit(m, qos_class=q, tenant=t,
                                      poison_id=pid),
            )
            if p.repeat_at_s is not None:
                # Same fingerprint, later arrival: the quarantine fence's
                # moment of truth.
                loop.schedule_at(
                    p.repeat_at_s * 1000.0,
                    lambda m=p.model, q=p.qos_class,
                    t=specs[p.model].tenant, pid=pid: sched.submit(
                        m, qos_class=q, tenant=t, poison_id=pid
                    ),
                )

        for f in sc.failures:
            if not 0 <= f.engine < sc.n_engines:
                raise ValueError(
                    f"failure names engine {f.engine} but the scenario has "
                    f"{sc.n_engines} engine(s)"
                )
            if f.chip is not None:
                if not 0 <= f.chip < engines[f.engine].width:
                    raise ValueError(
                        f"failure names chip {f.chip} of engine "
                        f"{f.engine}, a width-"
                        f"{engines[f.engine].width} unit"
                    )

                def _fail_chip(original=engines[f.engine], c=f.chip):
                    # Resolve the PHYSICAL chip to whichever unit owns
                    # it AT FIRE TIME: after a slice death + re-form,
                    # the chip belongs to a re-formed sub-slice (a
                    # fresh engine the scheduler enrolled mid-run) —
                    # failing the original dead object would let the
                    # sub-slice keep serving on dead hardware in a
                    # correlated rack event.
                    chip_id = original.chip_ids[c]
                    for e in sched.engines:
                        if e.alive and chip_id in e.chip_ids:
                            e.fail_chip(e.chip_ids.index(chip_id))
                            return
                    # Owner already dead: keep the bookkeeping honest
                    # so a LATER re-form can never resurrect the chip.
                    for e in sched.engines:
                        if chip_id in e.chip_ids:
                            e.dead_chips.add(e.chip_ids.index(chip_id))
                            return

                loop.schedule_at(f.at_s * 1000.0, _fail_chip)
            else:
                loop.schedule_at(
                    f.at_s * 1000.0, lambda e=engines[f.engine]: e.fail()
                )

        specs_by_name = {spec.name: spec for spec in sc.models}
        for c in sc.spec_collapses:
            target = specs_by_name.get(c.model)
            if target is None or not target.spec:
                raise ValueError(
                    f"acceptance collapse names {c.model!r}, which is not "
                    "a spec=True model in this scenario"
                )
            loop.schedule_at(
                c.at_s * 1000.0,
                lambda m=c.model, r=c.rate: spec_rates.__setitem__(m, r),
            )
            if c.heal_at_s is not None:
                loop.schedule_at(
                    c.heal_at_s * 1000.0,
                    lambda m=c.model, r=target.spec_acceptance: (
                        spec_rates.__setitem__(m, r)
                    ),
                )

        for g in sc.degradations:
            if not 0 <= g.engine < sc.n_engines:
                raise ValueError(
                    f"degradation names engine {g.engine} but the scenario "
                    f"has {sc.n_engines} engine(s)"
                )
            loop.schedule_at(
                g.at_s * 1000.0,
                lambda e=engines[g.engine], d=g: e.degrade(
                    d.factor, d.stall_ms
                ),
            )
            if g.heal_at_s is not None:
                loop.schedule_at(
                    g.heal_at_s * 1000.0,
                    lambda e=engines[g.engine]: e.heal_degradation(),
                )

        if sc.warm_start:
            sched.rebalance(rates=self._warm_start_rates(arrivals),
                            trigger="manual")
        sched.start_monitoring(until_ms=sc.duration_s * 1000.0)
        for e in engines:
            e.start()

        horizon_ms = (sc.duration_s + sc.drain_s) * 1000.0
        events = loop.run_until(horizon_ms)
        for e in engines:
            # Chunk trains still in flight at the horizon shed as stale
            # (the live drain's abort path) — conservation stays exact.
            e.flush_prefill_backlog()
        elapsed_ms = clock.now_ms()
        # Kept for post-run consumers that need the raw (mergeable) hop
        # sketches rather than the report's rendered quantiles — the
        # hop-drift CLI merges these against a live capture's.
        self.last_queues = queues

        # --- report -------------------------------------------------------
        models: Dict[str, Any] = {}
        for spec in sc.models:
            queue = queues.queue(spec.name)
            stats = queue.stats()
            rejected_total = sum(
                n for (mdl, _cls), n in sched.admission_rejected.items()
                if mdl == spec.name
            )
            classes: Dict[str, Any] = {}
            class_counters = queue.class_stats()
            for cls in sorted(
                set(class_counters)
                | set(class_offered.get(spec.name, {}))
            ):
                c = class_counters.get(cls, {})
                rejected = sched.admission_rejected.get(
                    (spec.name, cls), 0
                )
                classes[cls] = {
                    "offered": class_offered.get(spec.name, {}).get(cls, 0),
                    "admission_rejected": rejected,
                    "enqueued": int(c.get("enqueued", 0)),
                    "completed": int(c.get("completed", 0)),
                    "dropped": int(c.get("dropped", 0)),
                    "stale": int(c.get("stale", 0)),
                    "violations": int(c.get("violations", 0)),
                    "pending": int(c.get("depth", 0)),
                    "slo_attainment": slo_attainment(c),
                }
            models[spec.name] = {
                "slo_ms": spec.slo_ms,
                "arrivals": arrival_counts.get(spec.name, 0),
                "admission_rejected": rejected_total,
                "completed": int(stats["completed"]),
                "dropped": int(stats["dropped"]),
                "stale": int(stats["stale"]),
                "violations": int(stats["violations"]),
                "pending": int(stats["depth"]),
                # Poison verdicts are a subset of "dropped" (conservation
                # unchanged); keyed out only in poison scenarios so canon
                # reports keep their exact key set.
                **({"poisoned": int(queue.total_poisoned)}
                   if sc.poisons else {}),
                "slo_attainment": slo_attainment(stats),
                # Class-weighted attainment: the planner's pricing of a
                # miss (scheduler/replan.weighted_attainment — interactive
                # misses cost 4x best-effort ones).
                "weighted_attainment": weighted_attainment(class_counters),
                "classes": classes,
                "latency_p50_ms": stats["latency_p50_ms"],
                "latency_p95_ms": stats["latency_p95_ms"],
                "latency_p99_ms": stats["latency_p99_ms"],
                # Virtual-event hop ledger (sim slice of the live hop
                # taxonomy): feeds tools/run_sim.py --hop-drift.
                "hops": queue.hop_stats(),
            }
        chips: Dict[str, Any] = {}
        # sched.engines, not the construction list: slice re-formation
        # (SimScheduler._reform_slices) enrolls fresh units mid-run and
        # their execution must be accounted like anyone else's.
        engines = list(sched.engines)
        for e in engines:
            chips[e.engine_id] = {
                "busy_ms": e.busy_ms,
                "occupancy": e.occupancy(elapsed_ms),
                "slot_occupancy": e.slot_occupancy(),
                "batches": e.batches,
                "requests": e.requests,
                "cycles": e.cycle_count,
                "swaps": e.swap_count,
                "models": sorted(e.models),
                "alive": e.alive,
                "failed_at_ms": e.failed_at_ms,
            }
            if sc.engine_widths is not None:
                chips[e.engine_id]["width"] = e.width
                chips[e.engine_id]["chip_ids"] = list(e.chip_ids)
                chips[e.engine_id]["mesh_shape"] = e.mesh_shape
                chips[e.engine_id]["failed_chip"] = e.failed_chip
            if sched.gray is not None:
                chips[e.engine_id]["gray_state"] = sched.gray.state(
                    e.engine_id
                )
                chips[e.engine_id]["degraded"] = e.degraded
        audit = sched.audit.to_dicts()
        migrations = sum(
            1 for r in audit
            if r["diff"].get("engines_changed") and any(r["before"] or [])
        )
        return {
            "metric": "sim_report",
            "seed": sc.seed,
            "duration_s": sc.duration_s,
            "drain_s": sc.drain_s,
            "n_engines": sc.n_engines,
            **({"engine_widths": list(sc.engine_widths)}
               if sc.engine_widths is not None else {}),
            "rate_scale": sc.rate_scale,
            "decode_occupancy_model": sc.decode_occupancy_model,
            "events": events,
            "arrivals_total": len(arrivals),
            "arrivals_truncated_past_horizon": truncated,
            "arrivals_ignored_unregistered_model": ignored_models,
            "failures": [
                ({"at_s": f.at_s, "engine": f.engine} if f.chip is None
                 else {"at_s": f.at_s, "engine": f.engine, "chip": f.chip})
                for f in sc.failures
            ],
            "degradations": [
                {"at_s": g.at_s, "engine": g.engine, "factor": g.factor,
                 "stall_ms": g.stall_ms, "heal_at_s": g.heal_at_s}
                for g in sc.degradations
            ],
            # Query-of-death arm (conditional: poison-free scenarios stay
            # byte-identical): injection/fence/isolation ledger plus the
            # per-engine bisection cost actually paid.
            **({"poison": sched.poison_report()} if sc.poisons else {}),
            # Client-retry arm (conditional, same discipline): budget
            # stats, resubmission/denial counts, and the monitor-tick
            # windowed-attainment timeline the metastability pin grades.
            **({"retry": sched.retry_report()}
               if retry_cfg is not None else {}),
            # Speculative arm (conditional: pre-spec scenarios stay
            # byte-identical): planned vs final LIVE acceptance per spec
            # model, plus the injected collapse timeline.
            **({"spec": {
                "models": {
                    spec.name: {
                        "spec_tokens": spec.spec_tokens,
                        "planned_acceptance": spec.spec_acceptance,
                        "final_acceptance": spec_rates[spec.name],
                    }
                    for spec in sc.models if spec.spec
                },
                "collapses": [
                    {"at_s": c.at_s, "model": c.model, "rate": c.rate,
                     "heal_at_s": c.heal_at_s}
                    for c in sc.spec_collapses
                ],
            }} if spec_rates else {}),
            # Per-replica gray_state timeline (sim/report.gray_timeline
            # slices it per engine): every detector transition with its
            # virtual timestamp, plus the final verdicts.
            "gray": (
                None if sched.gray is None else {
                    "timeline": [dict(t) for t in sched.gray.transitions],
                    "final_states": sched.gray.states(),
                }
            ),
            "admission": (
                None if sched.admission is None else {
                    **sched.admission.stats(),
                    "final_state": {
                        spec.name: sched.admission.snapshot(
                            spec.name
                        )["state"]
                        for spec in sc.models
                    },
                }
            ),
            # SLO observatory block (conditional: pre-observatory
            # scenarios stay byte-identical). Alert timelines join
            # gray_timeline as first-class scenario output via
            # sim/report.alert_timeline.
            **({"observatory": {
                **sched.observatory.snapshot(),
                "alerts": {
                    "timeline": [
                        dict(t)
                        for t in sched.observatory.burn.transitions
                    ],
                    "final_states": sched.observatory.burn.states(),
                },
            }} if sched.observatory is not None else {}),
            "models": models,
            "chips": chips,
            "chips_used": sum(1 for e in engines if e.batches > 0),
            "schedule_changes": sched.schedule_changes,
            "migrations": migrations,
            "final_plan": [n.describe() for n in sched._current_plan],
            "audit": audit,
        }
