"""Built-in scenarios + synthetic profile fixtures.

The smoke scenario is the CI gate's fixture (``tools/run_sim.py
--smoke``): three models with distinct latency/memory shapes under a
mid-run traffic spike on one of them — enough to exercise saturate +
residue packing, a monitor-detected rate change, a live migration, and
SLO accounting, in well under a second of wall time. The profile
fixtures are synthetic (hermetic: the smoke must not move when committed
CPU tables are re-measured); committed-table replays go through
``tools/run_sim.py --profiles``.
"""

from __future__ import annotations

from typing import Dict

from ray_dynamic_batching_tpu.engine.workload import RatePattern
from ray_dynamic_batching_tpu.profiles.table import BatchProfile, ProfileRow
from ray_dynamic_batching_tpu.sim.simulator import (
    EngineDegradation,
    EngineFailure,
    Scenario,
    SimModelSpec,
)

MB = 1024 * 1024


def linear_profile(
    name: str,
    base_ms: float,
    per_sample_ms: float,
    weight_mb: int = 100,
    act_mb_per_sample: float = 1.0,
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    compile_ms: float = 1000.0,
    std_fraction: float = 0.0,
    mesh: str = "1x1",
) -> BatchProfile:
    """Latency = base + per_sample*batch — the canonical accelerator
    shape (same generator as ``tests/fixtures.py``, duplicated here so
    shipped tools never import the test tree). ``mesh`` stamps the rows
    as measured over that slice shape (per-slice latency, per-chip
    footprint — the ProfileRow mesh-axis contract)."""
    rows = [
        ProfileRow(
            batch_size=b,
            seq_len=0,
            latency_ms=base_ms + per_sample_ms * b,
            latency_std_ms=std_fraction * (base_ms + per_sample_ms * b),
            hbm_bytes=int((weight_mb + act_mb_per_sample * b) * MB),
            compile_ms=compile_ms,
            mesh=mesh,
        )
        for b in buckets
    ]
    return BatchProfile(name, rows)


def fixture_profiles() -> Dict[str, BatchProfile]:
    """Three models with distinct latency/memory shapes: a shufflenet-
    like sprinter, a steep burst-prone mid-tier (its SLO caps the
    bucket at b=16 / ~116 rps per chip, so a real spike SATURATES a
    chip), and a memory-fat heavyweight."""
    return {
        "fast": linear_profile("fast", base_ms=1.0, per_sample_ms=0.05,
                               weight_mb=20, act_mb_per_sample=0.2),
        "burst": linear_profile("burst", base_ms=10.0, per_sample_ms=8.0,
                                weight_mb=300, act_mb_per_sample=2.0),
        "fat": linear_profile("fat", base_ms=5.0, per_sample_ms=0.5,
                              weight_mb=4000, act_mb_per_sample=40.0),
    }


def smoke_scenario(seed: int = 0) -> Scenario:
    """60 virtual seconds, 3 chips, Poisson arrivals: ``burst`` spikes
    30 -> 160 rps mid-run — past its ~116 rps single-chip SLO capacity —
    so the monitor must catch the drift and migrate it across chips (and
    scale back down after). Expected story: ``fast``/``fat`` hold their
    SLOs throughout; ``burst`` sheds transiently during the detection
    lag, then recovers on the migrated plan."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=RatePattern(
                    "spike", base_rps=30.0, amplitude=130.0,
                    spike_at_s=25.0, spike_len_s=20.0,
                ),
            ),
            SimModelSpec(
                name="fat", slo_ms=800.0,
                pattern=RatePattern("constant", base_rps=7.0),
            ),
        ],
        duration_s=60.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=2.0,
    )


def overload_scenario(rate_scale: float = 1.0, seed: int = 0) -> Scenario:
    """The overload-soak fixture (``tools/run_overload_soak.py --sim``):
    one saturation-prone model, three chips, a mixed-class tenant
    population (80% best-effort bulk, 10% standard, 10% interactive) and
    token-bucket admission with the overload governor armed.

    At ``rate_scale=1.0`` (180 rps) capacity covers demand and every
    class serves clean. At 5x (900 rps offered) the story the gate
    asserts: the admission bucket clips the flood, the first saturated
    monitor ticks flip the governor to degraded (best-effort throttled to
    a trickle, interactive untouched), the class-then-deadline queue
    serves interactive first, and the backlog's stale discards land
    almost entirely on best-effort — interactive attainment holds its
    1x value while best-effort absorbs the shed, with every turned-away
    request accounted as rejected-at-admission."""
    return Scenario(
        models=[
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=RatePattern("constant", base_rps=180.0),
                class_mix={"interactive": 0.10, "standard": 0.10,
                           "best_effort": 0.80},
                tenant="mixed-pop",
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        rate_scale=rate_scale,
        max_queue_len=1024,
        monitoring_interval_s=2.0,
        admission={
            "rate_rps": 400.0,
            "burst": 50.0,
            "degraded_class_fractions": {
                "interactive": 1.0, "standard": 0.6, "best_effort": 0.1,
            },
            # Tuned to the fixture's observed overload dynamics: the
            # stale sweep holds depth near 0.16-0.18 of max_len at 5x, so
            # 0.15 catches the first saturated tick; recovery is gated by
            # the zero-recent-rejects rule, not these floors.
            "depth_high": 0.15,
            "depth_low": 0.02,
        },
    )


def chaos_scenario(seed: int = 0) -> Scenario:
    """The chaos conformance fixture (``tools/run_chaos_soak.py --sim``):
    two comfortably-provisioned models on 3 chips, one engine KILLED
    mid-run. Expected story: the monitor detects the death at its next
    tick, a heal replan migrates the dead chip's models to survivors,
    and — because capacity still covers demand — queued work completes
    within SLO: the failure costs at most a detection-window of sheds,
    never a silent stall. Roomy SLOs keep the accounting robust so the
    conformance gate grades the HEAL story, not knife-edge shedding."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=50.0),
            ),
            SimModelSpec(
                name="fat", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=6.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[EngineFailure(at_s=10.0, engine=0)],
    )


def straggler_scenario(seed: int = 0) -> Scenario:
    """The gray-failure conformance fixture (``tools/
    run_straggler_soak.py --sim``; first installment of ROADMAP item 3's
    slow-drip-straggler matrix): a 3-chip deployment at steady traffic,
    one chip running 10x SLOW (not dead — ``healthy()`` keeps lying)
    from t=8s until it heals at t=20s.

    Expected story: the gray monitor's ratio consensus flags chip0
    within a few 1 s ticks (suspect at 2 consecutive outlier ticks,
    probation 2 ticks later), the probation replan reprices it to
    fractional capacity — the heavy ``burst`` load moves to healthy
    chips while the light ``fast`` node keeps the straggler probed — and
    after the heal the clear-streak readmits it to full capacity.
    ``fast`` carries the interactive mix whose attainment the gate
    floors; ``burst`` is the load that HURTS while it sits on a 10x
    chip, so the detection window is visible in its attainment without
    sinking the gate."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
                class_mix={"interactive": 0.5, "standard": 0.5},
            ),
            # Past burst's ~116 rps single-chip SLO capacity: the packer
            # MUST spread the deployment over multiple chips, which is
            # what gives the gray monitor executing peers to form its
            # consensus from (a one-chip plan has nobody to compare).
            SimModelSpec(
                name="burst", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=150.0),
            ),
        ],
        duration_s=35.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=1.0,
        degradations=[
            EngineDegradation(at_s=8.0, engine=0, factor=10.0,
                              heal_at_s=20.0),
        ],
        gray={
            # Ratio-space observations (observed/expected ~1.0 healthy):
            # 3x the peer median is decisive, min_abs_ms below 1.0 keeps
            # healthy engines (ratio exactly 1.0) ungradeable as
            # outliers by construction. min_samples=2: sim ratios are
            # EXACT (no measurement noise — the hysteresis ticks are the
            # noise filter), and a lightly-loaded chip may only run a
            # couple of batches per 1 s tick. min_peers=1: ratio space
            # is model-agnostic, so a single healthy executing peer is a
            # valid consensus.
            "p50_ratio": 3.0,
            "p95_ratio": 3.0,
            "min_abs_ms": 0.5,
            "min_samples": 2,
            "min_peers": 1,
            "suspect_after": 2,
            "probation_after": 2,
            "heal_after": 2,
            "probation_capacity": 0.4,
        },
    )


def mesh_profiles() -> Dict[str, BatchProfile]:
    """The mesh-placement fixtures (ROADMAP item 2): the single-chip
    trio plus ``tp_llm``, a model with NO single-chip rows — it only
    exists as a 4-chip TP slice (fast steps) or a 2-chip half-slice
    (~2.2x slower per step, the collective-vs-compute tax of the
    narrower mesh). Per the ProfileRow mesh contract, hbm_bytes are
    PER-CHIP: the 1x2 rows carry twice the weight shard of the 1x4
    rows."""
    profiles = dict(fixture_profiles())
    tp4 = linear_profile(
        "tp_llm", base_ms=6.0, per_sample_ms=1.0, weight_mb=2500,
        act_mb_per_sample=4.0, mesh="1x4",
    )
    tp2 = linear_profile(
        "tp_llm", base_ms=13.0, per_sample_ms=2.2, weight_mb=5000,
        act_mb_per_sample=8.0, mesh="1x2",
    )
    profiles["tp_llm"] = BatchProfile("tp_llm", tp4.rows + tp2.rows)
    return profiles


def mesh_scenario(seed: int = 0) -> Scenario:
    """Mesh-sharded placement fixture (``tools/run_mesh_soak.py``): a
    cluster of one 4-chip TP slice, one 2-chip half-slice, and two
    single chips serving ``tp_llm`` (a model that only exists at mesh
    shapes 1x4/1x2) next to single-chip ``fast`` traffic. Expected
    story: the planner prices tp_llm from its 1x4 rows and pins it to
    the wide slice, fast packs onto the singles, and both hold their
    SLOs — the (model, mesh_shape) schedulable unit working end to
    end."""
    return Scenario(
        models=[
            SimModelSpec(
                name="tp_llm", slo_ms=400.0, mesh_shape="1x4",
                pattern=RatePattern("constant", base_rps=120.0),
                class_mix={"interactive": 0.5, "standard": 0.5},
            ),
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=4,
        engine_widths=[4, 2, 1, 1],
        seed=seed,
        monitoring_interval_s=2.0,
    )


def slice_failure_scenario(seed: int = 0) -> Scenario:
    """Slice-death fixture (the mesh half of the chaos story): same
    cluster as :func:`mesh_scenario`, but chip 1 of the 4-chip slice
    dies at t=10s. One dead chip fails the WHOLE slice (SliceDeadError
    semantics); the monitor detects it at the next tick, the surviving
    3 chips re-form as a 1x2 half-slice + a single, and the heal replan
    DEGRADES tp_llm to its 1x2 profile row on a surviving half-slice —
    slower steps, but the queue never starves. Roomy SLO so the gate
    grades the heal/degrade story, not knife-edge shedding."""
    return Scenario(
        models=[
            SimModelSpec(
                name="tp_llm", slo_ms=2500.0, mesh_shape="1x4",
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=40.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=4,
        engine_widths=[4, 2, 1, 1],
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[EngineFailure(at_s=10.0, engine=0, chip=1)],
    )


def correlated_failure_scenario(seed: int = 0) -> Scenario:
    """Correlated deaths (ROADMAP item 3's matrix, second entry): two of
    four chips die 400 ms apart — one rack event, not independent
    failures — under comfortable provisioning. Expected story: the
    monitor sees BOTH deaths (same tick or consecutive ticks), the heal
    replan(s) fold four chips' load onto two survivors, and because
    capacity still covers demand every model recovers: the event costs
    detection-window sheds, never a starved queue. Roomy SLOs keep the
    gate grading the heal story."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="fat", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=6.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=4,
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[
            EngineFailure(at_s=10.0, engine=0),
            EngineFailure(at_s=10.4, engine=1),
        ],
    )
