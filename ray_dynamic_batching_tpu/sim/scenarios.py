"""Built-in scenarios + synthetic profile fixtures.

The smoke scenario is the CI gate's fixture (``tools/run_sim.py
--smoke``): three models with distinct latency/memory shapes under a
mid-run traffic spike on one of them — enough to exercise saturate +
residue packing, a monitor-detected rate change, a live migration, and
SLO accounting, in well under a second of wall time. The profile
fixtures are synthetic (hermetic: the smoke must not move when committed
CPU tables are re-measured); committed-table replays go through
``tools/run_sim.py --profiles``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

from ray_dynamic_batching_tpu.engine.workload import RatePattern
from ray_dynamic_batching_tpu.profiles.table import BatchProfile, ProfileRow
from ray_dynamic_batching_tpu.sim.simulator import (
    AcceptanceCollapse,
    EngineDegradation,
    EngineFailure,
    PoisonInjection,
    Scenario,
    SimModelSpec,
)

MB = 1024 * 1024


def linear_profile(
    name: str,
    base_ms: float,
    per_sample_ms: float,
    weight_mb: int = 100,
    act_mb_per_sample: float = 1.0,
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    compile_ms: float = 1000.0,
    std_fraction: float = 0.0,
    mesh: str = "1x1",
    spec: str = "off",
) -> BatchProfile:
    """Latency = base + per_sample*batch — the canonical accelerator
    shape (same generator as ``tests/fixtures.py``, duplicated here so
    shipped tools never import the test tree). ``mesh`` stamps the rows
    as measured over that slice shape (per-slice latency, per-chip
    footprint — the ProfileRow mesh-axis contract); ``spec`` stamps them
    as speculative verify-ROUND costs (the ProfileRow spec-axis
    contract)."""
    rows = [
        ProfileRow(
            batch_size=b,
            seq_len=0,
            latency_ms=base_ms + per_sample_ms * b,
            latency_std_ms=std_fraction * (base_ms + per_sample_ms * b),
            hbm_bytes=int((weight_mb + act_mb_per_sample * b) * MB),
            compile_ms=compile_ms,
            mesh=mesh,
            spec=spec,
        )
        for b in buckets
    ]
    return BatchProfile(name, rows)


def fixture_profiles() -> Dict[str, BatchProfile]:
    """Three models with distinct latency/memory shapes: a shufflenet-
    like sprinter, a steep burst-prone mid-tier (its SLO caps the
    bucket at b=16 / ~116 rps per chip, so a real spike SATURATES a
    chip), and a memory-fat heavyweight."""
    return {
        "fast": linear_profile("fast", base_ms=1.0, per_sample_ms=0.05,
                               weight_mb=20, act_mb_per_sample=0.2),
        "burst": linear_profile("burst", base_ms=10.0, per_sample_ms=8.0,
                                weight_mb=300, act_mb_per_sample=2.0),
        "fat": linear_profile("fat", base_ms=5.0, per_sample_ms=0.5,
                              weight_mb=4000, act_mb_per_sample=40.0),
    }


def smoke_scenario(seed: int = 0) -> Scenario:
    """60 virtual seconds, 3 chips, Poisson arrivals: ``burst`` spikes
    30 -> 160 rps mid-run — past its ~116 rps single-chip SLO capacity —
    so the monitor must catch the drift and migrate it across chips (and
    scale back down after). Expected story: ``fast``/``fat`` hold their
    SLOs throughout; ``burst`` sheds transiently during the detection
    lag, then recovers on the migrated plan."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=RatePattern(
                    "spike", base_rps=30.0, amplitude=130.0,
                    spike_at_s=25.0, spike_len_s=20.0,
                ),
            ),
            SimModelSpec(
                name="fat", slo_ms=800.0,
                pattern=RatePattern("constant", base_rps=7.0),
            ),
        ],
        duration_s=60.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=2.0,
    )


def overload_scenario(rate_scale: float = 1.0, seed: int = 0) -> Scenario:
    """The overload-soak fixture (``tools/run_overload_soak.py --sim``):
    one saturation-prone model, three chips, a mixed-class tenant
    population (80% best-effort bulk, 10% standard, 10% interactive) and
    token-bucket admission with the overload governor armed.

    At ``rate_scale=1.0`` (180 rps) capacity covers demand and every
    class serves clean. At 5x (900 rps offered) the story the gate
    asserts: the admission bucket clips the flood, the first saturated
    monitor ticks flip the governor to degraded (best-effort throttled to
    a trickle, interactive untouched), the class-then-deadline queue
    serves interactive first, and the backlog's stale discards land
    almost entirely on best-effort — interactive attainment holds its
    1x value while best-effort absorbs the shed, with every turned-away
    request accounted as rejected-at-admission."""
    return Scenario(
        models=[
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=RatePattern("constant", base_rps=180.0),
                class_mix={"interactive": 0.10, "standard": 0.10,
                           "best_effort": 0.80},
                tenant="mixed-pop",
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        rate_scale=rate_scale,
        max_queue_len=1024,
        monitoring_interval_s=2.0,
        admission={
            "rate_rps": 400.0,
            "burst": 50.0,
            "degraded_class_fractions": {
                "interactive": 1.0, "standard": 0.6, "best_effort": 0.1,
            },
            # Tuned to the fixture's observed overload dynamics: the
            # stale sweep holds depth near 0.16-0.18 of max_len at 5x, so
            # 0.15 catches the first saturated tick; recovery is gated by
            # the zero-recent-rejects rule, not these floors.
            "depth_high": 0.15,
            "depth_low": 0.02,
        },
    )


def chaos_scenario(seed: int = 0) -> Scenario:
    """The chaos conformance fixture (``tools/run_chaos_soak.py --sim``):
    two comfortably-provisioned models on 3 chips, one engine KILLED
    mid-run. Expected story: the monitor detects the death at its next
    tick, a heal replan migrates the dead chip's models to survivors,
    and — because capacity still covers demand — queued work completes
    within SLO: the failure costs at most a detection-window of sheds,
    never a silent stall. Roomy SLOs keep the accounting robust so the
    conformance gate grades the HEAL story, not knife-edge shedding."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=50.0),
            ),
            SimModelSpec(
                name="fat", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=6.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[EngineFailure(at_s=10.0, engine=0)],
    )


def straggler_scenario(seed: int = 0) -> Scenario:
    """The gray-failure conformance fixture (``tools/
    run_straggler_soak.py --sim``; first installment of ROADMAP item 3's
    slow-drip-straggler matrix): a 3-chip deployment at steady traffic,
    one chip running 10x SLOW (not dead — ``healthy()`` keeps lying)
    from t=8s until it heals at t=20s.

    Expected story: the gray monitor's ratio consensus flags chip0
    within a few 1 s ticks (suspect at 2 consecutive outlier ticks,
    probation 2 ticks later), the probation replan reprices it to
    fractional capacity — the heavy ``burst`` load moves to healthy
    chips while the light ``fast`` node keeps the straggler probed — and
    after the heal the clear-streak readmits it to full capacity.
    ``fast`` carries the interactive mix whose attainment the gate
    floors; ``burst`` is the load that HURTS while it sits on a 10x
    chip, so the detection window is visible in its attainment without
    sinking the gate."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
                class_mix={"interactive": 0.5, "standard": 0.5},
            ),
            # Past burst's ~116 rps single-chip SLO capacity: the packer
            # MUST spread the deployment over multiple chips, which is
            # what gives the gray monitor executing peers to form its
            # consensus from (a one-chip plan has nobody to compare).
            SimModelSpec(
                name="burst", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=150.0),
            ),
        ],
        duration_s=35.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=1.0,
        degradations=[
            EngineDegradation(at_s=8.0, engine=0, factor=10.0,
                              heal_at_s=20.0),
        ],
        gray={
            # Ratio-space observations (observed/expected ~1.0 healthy):
            # 3x the peer median is decisive, min_abs_ms below 1.0 keeps
            # healthy engines (ratio exactly 1.0) ungradeable as
            # outliers by construction. min_samples=2: sim ratios are
            # EXACT (no measurement noise — the hysteresis ticks are the
            # noise filter), and a lightly-loaded chip may only run a
            # couple of batches per 1 s tick. min_peers=1: ratio space
            # is model-agnostic, so a single healthy executing peer is a
            # valid consensus.
            "p50_ratio": 3.0,
            "p95_ratio": 3.0,
            "min_abs_ms": 0.5,
            "min_samples": 2,
            "min_peers": 1,
            "suspect_after": 2,
            "probation_after": 2,
            "heal_after": 2,
            "probation_capacity": 0.4,
        },
    )


def mesh_profiles() -> Dict[str, BatchProfile]:
    """The mesh-placement fixtures (ROADMAP item 2): the single-chip
    trio plus ``tp_llm``, a model with NO single-chip rows — it only
    exists as a 4-chip TP slice (fast steps) or a 2-chip half-slice
    (~2.2x slower per step, the collective-vs-compute tax of the
    narrower mesh). Per the ProfileRow mesh contract, hbm_bytes are
    PER-CHIP: the 1x2 rows carry twice the weight shard of the 1x4
    rows."""
    profiles = dict(fixture_profiles())
    tp4 = linear_profile(
        "tp_llm", base_ms=6.0, per_sample_ms=1.0, weight_mb=2500,
        act_mb_per_sample=4.0, mesh="1x4",
    )
    tp2 = linear_profile(
        "tp_llm", base_ms=13.0, per_sample_ms=2.2, weight_mb=5000,
        act_mb_per_sample=8.0, mesh="1x2",
    )
    profiles["tp_llm"] = BatchProfile("tp_llm", tp4.rows + tp2.rows)
    return profiles


def mesh_scenario(seed: int = 0) -> Scenario:
    """Mesh-sharded placement fixture (``tools/run_mesh_soak.py``): a
    cluster of one 4-chip TP slice, one 2-chip half-slice, and two
    single chips serving ``tp_llm`` (a model that only exists at mesh
    shapes 1x4/1x2) next to single-chip ``fast`` traffic. Expected
    story: the planner prices tp_llm from its 1x4 rows and pins it to
    the wide slice, fast packs onto the singles, and both hold their
    SLOs — the (model, mesh_shape) schedulable unit working end to
    end."""
    return Scenario(
        models=[
            SimModelSpec(
                name="tp_llm", slo_ms=400.0, mesh_shape="1x4",
                pattern=RatePattern("constant", base_rps=120.0),
                class_mix={"interactive": 0.5, "standard": 0.5},
            ),
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=4,
        engine_widths=[4, 2, 1, 1],
        seed=seed,
        monitoring_interval_s=2.0,
    )


def slice_failure_scenario(seed: int = 0) -> Scenario:
    """Slice-death fixture (the mesh half of the chaos story): same
    cluster as :func:`mesh_scenario`, but chip 1 of the 4-chip slice
    dies at t=10s. One dead chip fails the WHOLE slice (SliceDeadError
    semantics); the monitor detects it at the next tick, the surviving
    3 chips re-form as a 1x2 half-slice + a single, and the heal replan
    DEGRADES tp_llm to its 1x2 profile row on a surviving half-slice —
    slower steps, but the queue never starves. Roomy SLO so the gate
    grades the heal/degrade story, not knife-edge shedding."""
    return Scenario(
        models=[
            SimModelSpec(
                name="tp_llm", slo_ms=2500.0, mesh_shape="1x4",
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=40.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=4,
        engine_widths=[4, 2, 1, 1],
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[EngineFailure(at_s=10.0, engine=0, chip=1)],
    )


# --- speculative decoding over the paged engine (ISSUE 13) ------------------


SPEC_ROUND_OVERHEAD = 1.4   # verify round cost vs a plain step (draft k+1
                            # steps + window verify on top of one target step)
SPEC_PROFILED_ACCEPTANCE = 0.7
SPEC_COLLAPSED_ACCEPTANCE = 0.05


def spec_profiles() -> Dict[str, BatchProfile]:
    """The spec-soak fixtures: the single-chip trio plus ``paged_llm``,
    a decode-shaped model with BOTH arms profiled — plain rows (one
    decode step) and ``spec="on"`` rows at ``SPEC_ROUND_OVERHEAD`` x the
    step cost (one verify round: draft k+1 cheap steps + the target's
    k+1-window verify). At the profiled acceptance 0.7 with k=4 a round
    emits E = (1-0.7^5)/0.3 ~ 2.77 tokens, so the spec arm's effective
    step cost is ~2x cheaper than plain — the Leviathan multiplier the
    paged engine's memory-bound decode path exists to collect."""
    profiles = dict(fixture_profiles())
    plain = linear_profile(
        "paged_llm", base_ms=8.0, per_sample_ms=1.0, weight_mb=1500,
        act_mb_per_sample=4.0,
    )
    spec = linear_profile(
        "paged_llm", base_ms=8.0 * SPEC_ROUND_OVERHEAD,
        per_sample_ms=1.0 * SPEC_ROUND_OVERHEAD, weight_mb=1800,
        act_mb_per_sample=4.0, spec="on",
    )
    profiles["paged_llm"] = BatchProfile("paged_llm",
                                         plain.rows + spec.rows)
    return profiles


def spec_scenario(spec: bool = False, collapse: bool = False,
                  seed: int = 0) -> Scenario:
    """The speculative-decoding soak fixture (``tools/run_spec_soak.py``),
    three arms over IDENTICAL traffic on the slot-priced (paged) cost
    model:

    - ``spec=False``: the plain paged arm — the baseline the win
      condition is measured against.
    - ``spec=True``: speculation at the profiled acceptance rate. The
      planner prices the spec rows ~2x cheaper per effective step, so
      the same 2 chips carry the offered load that mildly saturates the
      plain arm — the gate asserts it completes MORE at equal-or-better
      attainment (the ISSUE 13 sim win condition).
    - ``collapse=True``: adversarial prompts drive the LIVE acceptance
      to ~0 from t=8s to t=22s while the planner keeps its profiled
      belief. A verify round still emits >= 1 token, so the worst case
      is the round overhead (1.4x a plain step) — the gate floors
      throughput at a bounded factor of the plain arm and requires zero
      drops (client-visible errors)."""
    return Scenario(
        models=[
            SimModelSpec(
                name="paged_llm", slo_ms=900.0,
                pattern=RatePattern("constant", base_rps=850.0),
                spec=spec,
                spec_acceptance=SPEC_PROFILED_ACCEPTANCE,
                spec_tokens=4,
            ),
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=40.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=2,
        seed=seed,
        max_queue_len=16384,
        monitoring_interval_s=2.0,
        decode_occupancy_model="slot",
        spec_collapses=(
            [AcceptanceCollapse(
                at_s=8.0, model="paged_llm",
                rate=SPEC_COLLAPSED_ACCEPTANCE, heal_at_s=22.0,
            )] if collapse else []
        ),
    )


# --- chunked-prefill interleave (ISSUE 15) ----------------------------------

# One long prompt's full prefill beyond the profile row (the mono arm
# executes it inside the popped turn), and the chunk quantum the
# chunked arm spends between decode turns. 8 chunks per train: a
# 120 ms train vs a 15 ms stall bound.
INTERLEAVE_LONG_PREFILL_MS = 120.0
INTERLEAVE_CHUNK_MS = 15.0


def interleave_profiles() -> Dict[str, BatchProfile]:
    """The interleave-soak fixtures: a latency-sensitive interactive
    model sharing chips with a decode-shaped LLM whose traffic carries
    long prompts. The LLM's profile rows cover only the BUCKETED step —
    the long-prompt prefill cost rides per-request (SimRequest
    .prefill_ms), which is exactly what makes the two admission
    disciplines diverge."""
    return {
        "interactive": linear_profile(
            "interactive", base_ms=2.0, per_sample_ms=0.5,
            weight_mb=100, act_mb_per_sample=0.5,
        ),
        "llm_long": linear_profile(
            "llm_long", base_ms=10.0, per_sample_ms=1.5,
            weight_mb=1500, act_mb_per_sample=4.0,
        ),
    }


def interleave_scenario(chunked: bool = False, seed: int = 0) -> Scenario:
    """The interleave-soak fixture (``tools/run_interleave_soak.py``),
    two arms over IDENTICAL traffic on the slot-priced cost model: an
    interactive stream (SLO 250 ms) co-located with an LLM whose
    arrivals are 70% long prompts, plus a long-prompt FLASH CROWD
    (spike 12 -> 42 rps mid-run). The mono arm runs each long train
    inside its turn — every pop behind it waits the full 120 ms — so
    the interactive p50 inflates under the crowd; the chunked arm
    spends the same milliseconds as 15 ms budgeted chunk events between
    decode turns, and the interactive stream keeps its cadence. The
    gate pins the p50 gap, equal-or-better completions, and exact
    conservation."""
    return Scenario(
        models=[
            SimModelSpec(
                name="interactive", slo_ms=250.0,
                pattern=RatePattern("constant", base_rps=50.0),
            ),
            SimModelSpec(
                name="llm_long", slo_ms=4000.0,
                pattern=RatePattern(
                    "spike", base_rps=12.0, amplitude=30.0,
                    spike_at_s=10.0, spike_len_s=12.0,
                ),
                long_frac=0.7,
                long_prefill_ms=INTERLEAVE_LONG_PREFILL_MS,
            ),
        ],
        duration_s=40.0,
        drain_s=12.0,
        n_engines=2,
        seed=seed,
        max_queue_len=16384,
        monitoring_interval_s=2.0,
        decode_occupancy_model="slot",
        prefill_mode="chunked" if chunked else "mono",
        prefill_chunk_ms=INTERLEAVE_CHUNK_MS if chunked else 0.0,
        prefill_chunks_per_turn=1,
    )


# --- control-plane partition matrix (ISSUE 12) ------------------------------
#
# These fixtures parameterize sim/frontdoor.run_partition_sim, which rides
# the REAL fabric/store/frontdoor classes on the virtual clock — the same
# objects the live soak partitions, not simplified stand-ins. Node names:
# controllers ctl-A (initial leader) / ctl-B (cold standby), store
# substrate "log" + "lease", front-door shards fd-0..fd-{n-1}. Partition
# windows are virtual seconds in the fabric spec grammar
# (serve/fabric.parse_partition_spec).


@dataclass
class PartitionScenario:
    """One partition-defense story: a seeded 2x-oversubscribed admission
    flood over a sharded front door plus a leader/standby replicated
    store, with fabric partition windows cut mid-run. The CI smoke
    (tools/run_partition_soak.py --sim, tools/partition_smoke.json
    floors) replays each fixture twice and compares bytes."""

    name: str = "partition"
    seed: int = 0
    duration_s: float = 30.0
    drain_s: float = 5.0
    # Front door: global budget under an over-subscribed flood (the
    # budget must bind, so over-admission during the partition is
    # measurable against the allowance line).
    n_shards: int = 4
    rate_rps: float = 200.0
    burst: float = 200.0
    offered_rps: float = 400.0
    gossip_interval_s: float = 0.5
    # Fail-closed bound: 3 missed gossip rounds is a partition, not
    # jitter.
    staleness_bound_s: float = 1.5
    n_sessions: int = 40
    n_tenants: int = 4
    # Store: leader heartbeats a txn per tick; ctl-B is a COLD standby
    # (created at start, catches up only inside acquire_leadership —
    # the realistic new-controller-process failover, and what makes the
    # snapshot + tail-replay path the one under test).
    control_interval_s: float = 0.5
    lease_duration_s: float = 2.0
    snapshot_every: int = 16
    # Synthetic uptime: preloaded txns before the flood, so failover
    # replay cost is judged against a LONG log (the O(tail) ratchet).
    preload_txns: int = 0
    # Fabric chaos: partition windows + per-edge drop/delay/dup.
    partition_spec: str = ""
    edge_spec: str = ""


PARTITION_SCENARIOS: Tuple[str, ...] = (
    "symmetric_split",
    "leader_isolated",
    "gossip_only",
    "partition_during_flood",
    "heal_reconverge",
)


def partition_scenario(kind: str = "leader_isolated",
                       seed: int = 0) -> PartitionScenario:
    """The partition matrix. Each entry is one failure class from the
    ISSUE 12 taxonomy; ARCHITECTURE.md's "Partition semantics" table
    names each class's detector / degraded mode / client outcome /
    heal path — these fixtures are the executable versions."""
    if kind == "symmetric_split":
        # The control plane tears in half: the leader keeps two shards
        # but loses log, lease, AND the other half's gossip. Renewal
        # becomes unreachable -> the leader demotes on the lease-loss
        # path; the standby's side owns the quorum substrate and takes
        # over; BOTH gossip sides degrade fail-closed, then re-converge
        # on heal.
        return PartitionScenario(
            name=kind, seed=seed,
            partition_spec=("ctl-A+fd-0+fd-1|ctl-B+log+lease+fd-2+fd-3"
                            "@t=10:heal=10"),
        )
    if kind == "leader_isolated":
        # THE asymmetric case: the leader can renew its lease but not
        # reach the log. Without defense it would stay leader on a
        # heartbeat it cannot write under (split-brain); with it, the
        # bounded unreachable window self-demotes (store_unreachable),
        # the lease lapses unrenewed, and the standby — which CAN reach
        # the log — takes over by snapshot + tail replay. The long
        # preloaded log is what the O(tail) failover ratchet grades.
        return PartitionScenario(
            name=kind, seed=seed,
            preload_txns=400,
            partition_spec="ctl-A|log@t=10:heal=12",
        )
    if kind == "gossip_only":
        # Store untouched; the shard mesh splits 2|2. Each side's
        # ledgers lose half the fleet, degrade fail-closed at the
        # staleness bound (bounded over-admission, never unbounded),
        # and re-converge to exact global counts on heal.
        return PartitionScenario(
            name=kind, seed=seed,
            partition_spec="fd-0+fd-1|fd-2+fd-3@t=10:heal=10",
        )
    if kind == "partition_during_flood":
        # Correlated worst case: leader isolation AND a gossip split
        # open together at peak offered load (4x the budget), plus
        # chaos-duplicated gossip so the CRDT replacement's idempotence
        # is load-bearing, not decorative.
        return PartitionScenario(
            name=kind, seed=seed,
            offered_rps=800.0,
            preload_txns=200,
            partition_spec=("ctl-A|log@t=12:heal=8;"
                            "fd-0+fd-1|fd-2+fd-3@t=12:heal=8"),
            edge_spec="frontdoor.gossip=-1:dup:p0.2",
        )
    if kind == "heal_reconverge":
        # A minority shard drops off and returns; the long post-heal
        # window pins EXACT re-convergence (every shard's merged count
        # equals the oracle) and that degraded mode exits cleanly.
        return PartitionScenario(
            name=kind, seed=seed,
            duration_s=35.0,
            partition_spec="fd-0+fd-1+fd-2|fd-3@t=8:heal=6",
        )
    raise ValueError(
        f"unknown partition scenario {kind!r} "
        f"(known: {', '.join(PARTITION_SCENARIOS)})"
    )


def correlated_failure_scenario(seed: int = 0) -> Scenario:
    """Correlated deaths (ROADMAP item 3's matrix, second entry): two of
    four chips die 400 ms apart — one rack event, not independent
    failures — under comfortable provisioning. Expected story: the
    monitor sees BOTH deaths (same tick or consecutive ticks), the heal
    replan(s) fold four chips' load onto two survivors, and because
    capacity still covers demand every model recovers: the event costs
    detection-window sheds, never a starved queue. Roomy SLOs keep the
    gate grading the heal story."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="fat", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=6.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=4,
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[
            EngineFailure(at_s=10.0, engine=0),
            EngineFailure(at_s=10.4, engine=1),
        ],
    )


# --- SLO observatory (ISSUE 16) ---------------------------------------------

# Shared observatory knobs for the soak fixtures: SRE window lengths
# shrunk onto a sub-minute virtual horizon (fast 10 s / slow 30 s at
# 2 s epochs) so a 45 s scenario exercises the WHOLE alert lifecycle —
# fire, page, age out, resolve — and drift replays land every other
# monitor tick. page_burn stays multi-window: both horizons must burn.
OBSERVATORY_SOAK_POLICY = {
    "slo_target": 0.99,
    "fast_window_s": 10.0,
    "slow_window_s": 30.0,
    "epochs_per_window": 5,
    "warn_burn": 2.0,
    "page_burn": 10.0,
    "min_accounted": 20,
    "warn_after": 1,
    "page_after": 1,
    "resolve_after": 2,
    "resolved_hold_ticks": 3,
    "forecast_horizon_s": 5.0,
    "forecast_min_span_s": 3.0,
    "replay_every_ticks": 2,
    "drift_tolerance": 0.5,
    "drift_min_count": 5,
    "drift_min_abs_ms": 1.0,
}


def observatory_overload_scenario(seed: int = 0) -> Scenario:
    """The observatory soak's BURN arm (``tools/run_observatory_soak.py
    --sim``): ``burst`` spikes 30 -> 430 rps for 8 s — roughly double
    the ~230 rps two-chip SLO capacity — then subsides to a base load
    the pair serves trivially (no residual shed trickle to re-trip the
    alert after it clears). Expected story: the spike's sheds and
    violations torch the 1% error budget (fast AND slow burn past
    ``page_burn``), the alert machine walks ``ok -> warning -> page``;
    after the spike both windows rotate the incident out and the clear
    streak lands ``page -> resolved`` (then ``-> ok`` once the resolved
    hold expires). The gate pins that exact sequence, twice,
    byte-identically."""
    return Scenario(
        models=[
            SimModelSpec(
                name="burst", slo_ms=2000.0,
                pattern=RatePattern(
                    "spike", base_rps=30.0, amplitude=400.0,
                    spike_at_s=10.0, spike_len_s=8.0,
                ),
                class_mix={"interactive": 0.2, "best_effort": 0.8},
            ),
        ],
        duration_s=50.0,
        drain_s=5.0,
        n_engines=2,
        seed=seed,
        max_queue_len=256,
        monitoring_interval_s=1.0,
        observatory=dict(OBSERVATORY_SOAK_POLICY),
    )


def observatory_mispricing_scenario(seed: int = 0) -> Scenario:
    """The observatory soak's GUILTY-HOP arm: light steady traffic with
    a generous SLO (no burn alerts — this arm isolates the fidelity
    instrument), but the one chip runs 3x SLOW from t=1 s and never
    heals, with NO gray detection armed to catch it. The cost model
    keeps pricing ``engine.step`` from the profile row, so live runs
    ~3x its prediction — drift ~0.67 against the 0.5 tolerance. The
    gate asserts the ``fidelity_drift`` audit record names
    ``engine.step`` and does NOT name ``queue.wait`` (unpriced by
    contract: the profile tables never claimed to know queueing, so a
    mispriced engine cannot defame the queue)."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=40.0),
            ),
        ],
        duration_s=40.0,
        drain_s=5.0,
        n_engines=1,
        seed=seed,
        monitoring_interval_s=1.0,
        degradations=[
            EngineDegradation(at_s=1.0, engine=0, factor=3.0),
        ],
        observatory=dict(OBSERVATORY_SOAK_POLICY),
    )


# --- compound-fault scenario matrix (ISSUE 19) -------------------------------
#
# Single-fault soaks prove each defense in isolation; production outages
# are COMPOUND — a spike lands while a chip is dying, a query of death
# arrives mid-overload, and the client retry loop amplifies whatever is
# already wrong (Bronson et al.'s metastable-failure shape). The matrix
# composes the existing fault axes into named compound scenarios over
# one shared deployment, with the client-retry model armed in EVERY
# entry: retries are the amplifier that turns a transient fault into a
# sustained one, so every compound story is graded with amplification
# live. ``defenses=True`` arms the budget fraction + the governor's
# congested floor; ``defenses=False`` is the naive-client control arm
# (unbounded retries, no congested coupling) the metastability pin must
# grade STRICTLY worse.

# Every fault fires inside [COMPOUND_FAULT_AT_S, COMPOUND_FAULT_END_S];
# the metastability pin compares windowed attainment before the fault
# against the window after COMPOUND_RECOVER_BY_S — recovery must be
# monotone and complete within the bounded horizon.
COMPOUND_FAULT_AT_S = 12.0
COMPOUND_FAULT_END_S = 24.0
COMPOUND_RECOVER_BY_S = 38.0
COMPOUND_DURATION_S = 50.0

# The fault axes a compound name may compose ("retries" is implicit in
# every entry and accepted in names for readability).
COMPOUND_AXES: Tuple[str, ...] = (
    "spike", "death", "slowchip", "poison", "retries",
)

COMPOUND_SCENARIOS: Tuple[str, ...] = (
    "spike+retries",          # overload + retry storm
    "death+retries",          # engine death + retry storm
    "slowchip+retries",       # gray straggler + retry storm
    "poison+retries",         # query of death + retry storm
    "spike+death",            # overload lands on a dying cluster
    "spike+poison",           # query of death arrives mid-overload
    "death+slowchip",         # death + gray straggler (half-lame heal)
    "spike+death+poison",     # the kitchen sink
)

# The designated metastability scenario: the matrix soak runs its
# control arm (defenses=False) alongside and pins that the defended arm
# recovers to >= 0.95x pre-fault attainment within the horizon while
# the naive arm recovers strictly worse.
METASTABILITY_SCENARIO = "spike+death"


def compound_scenario(name: str, defenses: bool = True,
                      seed: int = 0) -> Scenario:
    """Build one named compound-fault scenario (cross-product grammar:
    ``axis+axis[+axis]`` over :data:`COMPOUND_AXES`).

    Shared deployment: 3 chips, ``fast`` (interactive mix, 60 rps) +
    ``burst`` (150 rps steady — ~0.65 of the 2-chip post-death
    capacity) with token-bucket admission armed. Client retries: up to
    6 attempts, 50 ms exponential backoff — every stale shed re-enters
    the front door as fresh demand. The defended arm bounds that to
    0.25x first-attempt volume and lets the governor's congested state
    zero it; the control arm retries without bound."""
    axes = [a for a in name.split("+") if a]
    unknown = set(axes) - set(COMPOUND_AXES)
    if unknown:
        raise ValueError(
            f"unknown compound axis(es) {sorted(unknown)} in {name!r}; "
            f"known: {', '.join(COMPOUND_AXES)}"
        )
    spike = "spike" in axes
    # Base demand sits at ~0.65 of POST-death capacity (150 rps burst on
    # the 2 surviving chips' ~230 rps): room enough for the defended arm
    # to recover fully within the horizon, tight enough that unbounded
    # retry amplification (up to 5 re-dispatches per shed) keeps the
    # naive arm shedding past it — the metastable gap the pin grades.
    burst_pattern = (
        RatePattern("spike", base_rps=150.0, amplitude=250.0,
                    spike_at_s=COMPOUND_FAULT_AT_S, spike_len_s=10.0)
        if spike else RatePattern("constant", base_rps=150.0)
    )
    sc = Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=400.0,
                pattern=RatePattern("constant", base_rps=60.0),
                class_mix={"interactive": 0.5, "standard": 0.5},
            ),
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=burst_pattern,
                class_mix={"interactive": 0.2, "standard": 0.3,
                           "best_effort": 0.5},
            ),
        ],
        duration_s=COMPOUND_DURATION_S,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        max_queue_len=2048,
        monitoring_interval_s=1.0,
        admission={
            "rate_rps": 500.0,
            "burst": 60.0,
            "degraded_class_fractions": {
                "interactive": 1.0, "standard": 0.6, "best_effort": 0.1,
            },
            "depth_high": 0.15,
            "depth_low": 0.02,
            # The congested floor is a DEFENSE: while first-attempt
            # compliance sits under it, the governor zeroes the retry
            # budget so recovery is monotone. The control arm runs
            # without it (0.0 = disabled).
            **({"congested_floor": 0.55, "congested_exit": 0.85}
               if defenses else {}),
        },
        retry={
            "max_attempts": 6,
            "backoff_ms": 50.0,
            # Work-conserving bound vs naive unbounded clients.
            "budget_fraction": 0.25 if defenses else None,
            "budget_window": 256,
            "min_first_attempts": 16,
        },
    )
    if "death" in axes:
        sc.failures.append(
            EngineFailure(at_s=COMPOUND_FAULT_AT_S, engine=2)
        )
    if "slowchip" in axes:
        sc.degradations.append(
            EngineDegradation(at_s=COMPOUND_FAULT_AT_S, engine=0,
                              factor=8.0,
                              heal_at_s=COMPOUND_FAULT_END_S)
        )
        # Gray detection armed (straggler_scenario's ratio-space knobs)
        # so the straggler is repriced, not just endured.
        sc.gray = {
            "p50_ratio": 3.0, "p95_ratio": 3.0, "min_abs_ms": 0.5,
            "min_samples": 2, "min_peers": 1, "suspect_after": 2,
            "probation_after": 2, "heal_after": 2,
            "probation_capacity": 0.4,
        }
    if "poison" in axes:
        sc.poisons.append(
            PoisonInjection(at_s=COMPOUND_FAULT_AT_S + 2.0,
                            model="burst",
                            repeat_at_s=COMPOUND_RECOVER_BY_S - 8.0)
        )
    return sc


def observatory_steady_scenario(seed: int = 0) -> Scenario:
    """The observatory soak's SILENCE arm: comfortably-provisioned
    steady traffic, nothing injected. Expected story: ZERO alert
    transitions, zero fidelity-drift records (``engine.step`` graded
    clean, ``queue.wait`` ungraded by contract), and a working
    forecaster — predictions scored every horizon with small error.
    An observatory that pages on a healthy cluster is worse than none;
    this arm is the false-positive gate."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=50.0),
            ),
            SimModelSpec(
                name="fat", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=6.0),
            ),
        ],
        duration_s=40.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=1.0,
        observatory=dict(OBSERVATORY_SOAK_POLICY),
    )
