"""Built-in scenarios + synthetic profile fixtures.

The smoke scenario is the CI gate's fixture (``tools/run_sim.py
--smoke``): three models with distinct latency/memory shapes under a
mid-run traffic spike on one of them — enough to exercise saturate +
residue packing, a monitor-detected rate change, a live migration, and
SLO accounting, in well under a second of wall time. The profile
fixtures are synthetic (hermetic: the smoke must not move when committed
CPU tables are re-measured); committed-table replays go through
``tools/run_sim.py --profiles``.
"""

from __future__ import annotations

from typing import Dict

from ray_dynamic_batching_tpu.engine.workload import RatePattern
from ray_dynamic_batching_tpu.profiles.table import BatchProfile, ProfileRow
from ray_dynamic_batching_tpu.sim.simulator import (
    EngineFailure,
    Scenario,
    SimModelSpec,
)

MB = 1024 * 1024


def linear_profile(
    name: str,
    base_ms: float,
    per_sample_ms: float,
    weight_mb: int = 100,
    act_mb_per_sample: float = 1.0,
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    compile_ms: float = 1000.0,
    std_fraction: float = 0.0,
) -> BatchProfile:
    """Latency = base + per_sample*batch — the canonical accelerator
    shape (same generator as ``tests/fixtures.py``, duplicated here so
    shipped tools never import the test tree)."""
    rows = [
        ProfileRow(
            batch_size=b,
            seq_len=0,
            latency_ms=base_ms + per_sample_ms * b,
            latency_std_ms=std_fraction * (base_ms + per_sample_ms * b),
            hbm_bytes=int((weight_mb + act_mb_per_sample * b) * MB),
            compile_ms=compile_ms,
        )
        for b in buckets
    ]
    return BatchProfile(name, rows)


def fixture_profiles() -> Dict[str, BatchProfile]:
    """Three models with distinct latency/memory shapes: a shufflenet-
    like sprinter, a steep burst-prone mid-tier (its SLO caps the
    bucket at b=16 / ~116 rps per chip, so a real spike SATURATES a
    chip), and a memory-fat heavyweight."""
    return {
        "fast": linear_profile("fast", base_ms=1.0, per_sample_ms=0.05,
                               weight_mb=20, act_mb_per_sample=0.2),
        "burst": linear_profile("burst", base_ms=10.0, per_sample_ms=8.0,
                                weight_mb=300, act_mb_per_sample=2.0),
        "fat": linear_profile("fat", base_ms=5.0, per_sample_ms=0.5,
                              weight_mb=4000, act_mb_per_sample=40.0),
    }


def smoke_scenario(seed: int = 0) -> Scenario:
    """60 virtual seconds, 3 chips, Poisson arrivals: ``burst`` spikes
    30 -> 160 rps mid-run — past its ~116 rps single-chip SLO capacity —
    so the monitor must catch the drift and migrate it across chips (and
    scale back down after). Expected story: ``fast``/``fat`` hold their
    SLOs throughout; ``burst`` sheds transiently during the detection
    lag, then recovers on the migrated plan."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=200.0,
                pattern=RatePattern("constant", base_rps=60.0),
            ),
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=RatePattern(
                    "spike", base_rps=30.0, amplitude=130.0,
                    spike_at_s=25.0, spike_len_s=20.0,
                ),
            ),
            SimModelSpec(
                name="fat", slo_ms=800.0,
                pattern=RatePattern("constant", base_rps=7.0),
            ),
        ],
        duration_s=60.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=2.0,
    )


def overload_scenario(rate_scale: float = 1.0, seed: int = 0) -> Scenario:
    """The overload-soak fixture (``tools/run_overload_soak.py --sim``):
    one saturation-prone model, three chips, a mixed-class tenant
    population (80% best-effort bulk, 10% standard, 10% interactive) and
    token-bucket admission with the overload governor armed.

    At ``rate_scale=1.0`` (180 rps) capacity covers demand and every
    class serves clean. At 5x (900 rps offered) the story the gate
    asserts: the admission bucket clips the flood, the first saturated
    monitor ticks flip the governor to degraded (best-effort throttled to
    a trickle, interactive untouched), the class-then-deadline queue
    serves interactive first, and the backlog's stale discards land
    almost entirely on best-effort — interactive attainment holds its
    1x value while best-effort absorbs the shed, with every turned-away
    request accounted as rejected-at-admission."""
    return Scenario(
        models=[
            SimModelSpec(
                name="burst", slo_ms=500.0,
                pattern=RatePattern("constant", base_rps=180.0),
                class_mix={"interactive": 0.10, "standard": 0.10,
                           "best_effort": 0.80},
                tenant="mixed-pop",
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        rate_scale=rate_scale,
        max_queue_len=1024,
        monitoring_interval_s=2.0,
        admission={
            "rate_rps": 400.0,
            "burst": 50.0,
            "degraded_class_fractions": {
                "interactive": 1.0, "standard": 0.6, "best_effort": 0.1,
            },
            # Tuned to the fixture's observed overload dynamics: the
            # stale sweep holds depth near 0.16-0.18 of max_len at 5x, so
            # 0.15 catches the first saturated tick; recovery is gated by
            # the zero-recent-rejects rule, not these floors.
            "depth_high": 0.15,
            "depth_low": 0.02,
        },
    )


def chaos_scenario(seed: int = 0) -> Scenario:
    """The chaos conformance fixture (``tools/run_chaos_soak.py --sim``):
    two comfortably-provisioned models on 3 chips, one engine KILLED
    mid-run. Expected story: the monitor detects the death at its next
    tick, a heal replan migrates the dead chip's models to survivors,
    and — because capacity still covers demand — queued work completes
    within SLO: the failure costs at most a detection-window of sheds,
    never a silent stall. Roomy SLOs keep the accounting robust so the
    conformance gate grades the HEAL story, not knife-edge shedding."""
    return Scenario(
        models=[
            SimModelSpec(
                name="fast", slo_ms=2000.0,
                pattern=RatePattern("constant", base_rps=50.0),
            ),
            SimModelSpec(
                name="fat", slo_ms=4000.0,
                pattern=RatePattern("constant", base_rps=6.0),
            ),
        ],
        duration_s=30.0,
        drain_s=5.0,
        n_engines=3,
        seed=seed,
        monitoring_interval_s=2.0,
        failures=[EngineFailure(at_s=10.0, engine=0)],
    )
