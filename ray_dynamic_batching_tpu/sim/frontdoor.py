"""Sim twin of the distributed control plane (ISSUE 11).

The million-user scenarios only run here: this module drives the REAL
control-plane classes — :class:`~ray_dynamic_batching_tpu.serve.
frontdoor.FrontDoor` (shard ring + gossip ledgers), :class:`~ray_dynamic_
batching_tpu.serve.store.ReplicatedStore`/:class:`StoreLog`/:class:`Leader
Lease` (epoch-fenced failover), and :class:`~ray_dynamic_batching_tpu.
serve.router.PrefixDigestDirectory` (cluster-wide prefix routing) — on
the virtual clock, so shard gossip, store failover, and digest routing
are deterministic events and two same-seed runs render byte-identical
reports.

One run plays THREE sub-twins over one seeded flood:

- **gossip budget**: arrivals admit through the sharded front door while
  gossip rounds fire on the virtual clock; the report carries the drift
  audit (fleet admissions vs the central oracle, bounded by
  ``(N-1) * rate * staleness``).
- **store failover**: a leader controller heartbeats transactions into
  the shared log until it is killed mid-flood; the standby acquires the
  lease when it lapses (epoch bump, log fence) and the deposed leader's
  next write is REJECTED — the report pins the epoch numbers and the
  :class:`StaleEpochError`.
- **digest routing**: admitted requests route over model replicas whose
  prefix caches publish digest chains into a real
  ``PrefixDigestDirectory``; the same workload replays with digest
  routing OFF (pure pow-2) as the per-replica baseline arm, so the
  cluster-hit-rate-beats-baseline claim is measured, not assumed.

The gate (tools/run_frontdoor_soak.py --sim) asserts determinism,
accounting conservation, budget conservation within the staleness
bound, the epoch-fenced failover, and the hit-rate win.
"""

from __future__ import annotations

import collections
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ray_dynamic_batching_tpu.scheduler.audit import AuditLog
from ray_dynamic_batching_tpu.serve.fabric import (
    ControlFabric,
    FabricUnreachable,
)
from ray_dynamic_batching_tpu.serve.frontdoor import FrontDoor
from ray_dynamic_batching_tpu.serve.router import PrefixDigestDirectory
from ray_dynamic_batching_tpu.serve.store import (
    LeaderLease,
    ReplicatedStore,
    StaleEpochError,
    StoreLog,
)
from ray_dynamic_batching_tpu.sim.clock import EventLoop, VirtualClock

DEPLOYMENT = "llm"


@dataclass
class FrontDoorScenario:
    """Deterministic control-plane flood: parameters shared by the CI
    smoke (tools/frontdoor_smoke.json canon) and ad-hoc what-ifs."""

    seed: int = 0
    duration_s: float = 30.0
    # Front door: 4 shards, global budget 200 rps under a 400 rps flood
    # (2x over-subscribed — the budget must bind).
    n_shards: int = 4
    rate_rps: float = 200.0
    burst: float = 200.0
    offered_rps: float = 400.0
    gossip_interval_s: float = 0.5
    # Store failover: leader killed mid-flood; standby takes over when
    # the lease lapses.
    control_interval_s: float = 0.5
    lease_duration_s: float = 2.0
    kill_leader_at_s: float = 12.0
    # Digest routing: model replicas with bounded prefix caches serving
    # a prompt-family mix (a few hot system prompts + cold tails).
    n_replicas: int = 3
    n_families: int = 6
    family_chain_pages: int = 3
    replica_cache_entries: int = 4
    n_sessions: int = 40
    hot_family_bias: float = 0.7  # fraction of traffic on 2 hot families


class _ModelReplica:
    """Digest-routing model replica: a bounded LRU of digest-chain keys
    standing in for the paged prefix cache, plus a busy counter standing
    in for queue depth."""

    def __init__(self, rid: str, cache_entries: int) -> None:
        self.rid = rid
        self.cache: "collections.OrderedDict" = collections.OrderedDict()
        self.cache_entries = cache_entries
        self.busy = 0
        self.completed = 0
        self.hits = 0
        self.misses = 0

    def digests(self) -> Dict[str, int]:
        return {key: level for key, level in self.cache.items()}

    def serve(self, chain: List[str]) -> bool:
        """True on a prefix hit (deepest chain key cached)."""
        hit = any(key in self.cache for key in reversed(chain))
        if hit:
            self.hits += 1
            deepest = next(k for k in reversed(chain) if k in self.cache)
            self.cache.move_to_end(deepest)
        else:
            self.misses += 1
        # Serving publishes the full chain (the admission inserts every
        # full-page prefix, exactly like PagedPrefixCache.insert).
        for level, key in enumerate(chain, start=1):
            if key not in self.cache:
                self.cache[key] = level
        while len(self.cache) > self.cache_entries:
            self.cache.popitem(last=False)
        return hit


def _family_chain(family: int, pages: int) -> List[str]:
    """Synthetic digest chain for a prompt family — stable strings play
    the role of the blake2b level keys (the directory treats keys as
    opaque)."""
    return [f"fam{family}:{level}" for level in range(1, pages + 1)]


def _run_arm(sc: FrontDoorScenario, digest_routing: bool) -> Dict[str, Any]:
    """One full deterministic run; the baseline arm re-runs the same
    seed with digest routing disabled."""
    clock = VirtualClock()
    loop = EventLoop(clock)
    rng = random.Random(sc.seed)

    # --- front door (real classes, virtual clock) -----------------------
    fd = FrontDoor(n_shards=sc.n_shards, clock=clock.now_s,
                   gossip_interval_s=sc.gossip_interval_s)
    fd.configure(DEPLOYMENT, sc.rate_rps, sc.burst)

    # --- replicated store (real classes, virtual clock) -----------------
    log = StoreLog(clock=clock.now_s)
    lease = LeaderLease(sc.lease_duration_s, clock=clock.now_s)
    leader = ReplicatedStore(log, lease, "ctl-A")
    assert leader.acquire_leadership() == 1
    standby = ReplicatedStore(log, lease, "ctl-B")
    store_state: Dict[str, Any] = {
        "leader": "ctl-A", "epoch": 1, "failover_at_s": None,
        "stale_write_rejected": False, "stale_error": "",
        "heartbeats": {"ctl-A": 0, "ctl-B": 0},
        "completions_while_leaderless": 0,
    }

    # --- digest-routing data plane --------------------------------------
    replicas = {f"r{i}": _ModelReplica(f"r{i}", sc.replica_cache_entries)
                for i in range(sc.n_replicas)}
    directory = PrefixDigestDirectory()
    counts = {"arrivals": 0, "admitted": 0, "rejected": 0, "completed": 0,
              "errors": 0}

    def route(chain: List[str]) -> _ModelReplica:
        ids = sorted(replicas)
        if digest_routing and chain:
            depth, holders = directory.best(chain, ids)
            if depth > 0:
                ids = sorted(holders)
        if len(ids) == 1:
            return replicas[ids[0]]
        a, b = rng.sample(ids, 2)
        return replicas[a if replicas[a].busy <= replicas[b].busy else b]

    def service_time(hit: bool, chain: List[str]) -> float:
        # Prefill dominates cold admissions; a prefix hit skips it.
        return 0.01 + (0.0 if hit else 0.01 * len(chain))

    def arrival(session: int, family: int) -> None:
        counts["arrivals"] += 1
        payload = {"session_id": f"s{session}"}
        _, ok, _retry = fd.admit(DEPLOYMENT, payload=payload,
                                 tenant=f"t{session % 4}")
        if not ok:
            counts["rejected"] += 1
            return
        counts["admitted"] += 1
        chain = _family_chain(family, sc.family_chain_pages)
        replica = route(chain)
        hit = replica.serve(chain)
        replica.busy += 1

        def complete(r=replica) -> None:
            r.busy -= 1
            r.completed += 1
            counts["completed"] += 1
            if store_state["leader"] is None:
                store_state["completions_while_leaderless"] += 1

        loop.schedule_in(service_time(hit, chain) * 1000.0, complete)

    # Seeded arrival schedule (exponential gaps), fixed up front so both
    # arms replay the identical offered load.
    t_ms = 0.0
    horizon_ms = sc.duration_s * 1000.0
    hot = (0, 1)
    while True:
        t_ms += rng.expovariate(sc.offered_rps) * 1000.0
        if t_ms >= horizon_ms:
            break
        session = rng.randrange(sc.n_sessions)
        if rng.random() < sc.hot_family_bias:
            family = hot[rng.randrange(len(hot))]
        else:
            family = 2 + rng.randrange(sc.n_families - 2)
        loop.schedule_at(t_ms, lambda s=session, f=family: arrival(s, f))

    # Gossip rounds on the virtual clock.
    def gossip() -> None:
        fd.gossip_round()
        if clock.now_ms() + sc.gossip_interval_s * 1000.0 < horizon_ms:
            loop.schedule_in(sc.gossip_interval_s * 1000.0, gossip)

    loop.schedule_in(sc.gossip_interval_s * 1000.0, gossip)

    # Control ticks: the live leader heartbeats a transaction; the
    # standby replays the log and takes over once the lease lapses.
    # Digest publications ride the control tick, like the live
    # controller's _publish_prefix_digests.
    def control_tick() -> None:
        now_s = clock.now_s()
        if store_state["leader"] == "ctl-A" \
                and now_s >= sc.kill_leader_at_s:
            store_state["leader"] = None  # killed: stops renewing
        active = {"ctl-A": leader, "ctl-B": standby}.get(
            store_state["leader"] or ""
        )
        if active is not None and active.renew():
            with active.txn() as txn:
                txn.put_json("serve:heartbeat", {
                    "owner": active.owner,
                    "tick": store_state["heartbeats"][active.owner] + 1,
                })
            store_state["heartbeats"][active.owner] += 1
        elif store_state["leader"] is None:
            epoch = standby.acquire_leadership()
            if epoch is not None:
                store_state["leader"] = "ctl-B"
                store_state["epoch"] = epoch
                store_state["failover_at_s"] = round(now_s, 3)
                # The deposed leader wakes up and tries to finish a
                # half-done write: the fence must reject it.
                try:
                    with leader.txn() as txn:
                        txn.put_json("serve:heartbeat",
                                     {"owner": "ctl-A", "tick": -1})
                except StaleEpochError as e:
                    store_state["stale_write_rejected"] = True
                    store_state["stale_error"] = str(e)
        for rid in sorted(replicas):
            directory.publish(rid, 128, replicas[rid].digests())
        if clock.now_ms() + sc.control_interval_s * 1000.0 < horizon_ms:
            loop.schedule_in(sc.control_interval_s * 1000.0, control_tick)

    loop.schedule_in(sc.control_interval_s * 1000.0, control_tick)

    # Drift audited AT the flood horizon (the allowance line keeps
    # growing while arrivals have stopped — auditing later would read
    # artificially under-admitted), then drain so in-flight completions
    # land.
    loop.run_until(horizon_ms)
    drift = fd.drift_audit(DEPLOYMENT)
    loop.run_until(horizon_ms + 5_000.0)
    hits = sum(r.hits for r in replicas.values())
    misses = sum(r.misses for r in replicas.values())
    return {
        "digest_routing": digest_routing,
        "counts": counts,
        "drift": drift,
        "frontdoor": fd.stats(),
        "store": {
            **{k: v for k, v in store_state.items()
               if k != "stale_error"},
            "stale_error": store_state["stale_error"][:80],
            "log_records": len(log),
            "rejected_appends": log.rejected_appends,
            "fence_epoch": log.fence_epoch,
        },
        "routing": {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / max(1, hits + misses), 4),
            "per_replica": {
                rid: {"completed": r.completed, "hits": r.hits,
                      "misses": r.misses}
                for rid, r in sorted(replicas.items())
            },
            "directory_publishes": directory.snapshot()["publishes"],
        },
    }


def run_frontdoor_sim(
    scenario: Optional[FrontDoorScenario] = None,
) -> Dict[str, Any]:
    """Both arms (digest routing on / off) over the identical seeded
    flood; the gate compares their hit rates and checks every
    conservation invariant on the routed arm."""
    sc = scenario or FrontDoorScenario()
    return {
        "scenario": vars(sc),
        "routed": _run_arm(sc, digest_routing=True),
        "baseline": _run_arm(sc, digest_routing=False),
    }


# --- partition defense (ISSUE 12) -------------------------------------------


def run_partition_sim(scenario: Any) -> Dict[str, Any]:
    """One partition-matrix arm (sim/scenarios.PartitionScenario) on the
    virtual clock, riding the REAL classes end to end: a ControlFabric
    with the scenario's partition windows (delays are events on the
    event loop — byte-deterministic), a sharded FrontDoor with the
    fail-closed staleness bound armed, and a ReplicatedStore leader +
    COLD standby with snapshot compaction.

    The story the gate grades: the flood admits under the global
    budget; the partition opens mid-run; the leader either self-demotes
    (appends unreachable — the asymmetric case) or demotes on lease
    loss; the standby takes over by snapshot + tail replay (O(tail));
    the deposed epoch's post-heal write is REJECTED at the fence (zero
    split-brain commits); gossip-partitioned ledgers degrade fail-closed
    within the audited bound and re-converge to exact global counts on
    heal; the data plane never surfaces a system error."""
    sc = scenario
    clock = VirtualClock()
    loop = EventLoop(clock)
    rng = random.Random(sc.seed)

    fabric = ControlFabric(
        clock=clock.now_s,
        scheduler=lambda delay_ms, fn: loop.schedule_in(delay_ms, fn),
        seed=sc.seed,
        partition_spec=sc.partition_spec,
        edge_spec=sc.edge_spec,
    )

    # --- sharded front door, fail-closed bound armed ---------------------
    fd = FrontDoor(n_shards=sc.n_shards, clock=clock.now_s,
                   gossip_interval_s=sc.gossip_interval_s,
                   fabric=fabric, staleness_bound_s=sc.staleness_bound_s)
    fd.configure(DEPLOYMENT, sc.rate_rps, sc.burst)

    # --- replicated store: leader + cold standby -------------------------
    log = StoreLog(clock=clock.now_s)
    lease = LeaderLease(sc.lease_duration_s, clock=clock.now_s)
    leader = ReplicatedStore(log, lease, "ctl-A", fabric=fabric,
                             clock=clock.now_s,
                             snapshot_every=sc.snapshot_every)
    standby = ReplicatedStore(log, lease, "ctl-B", fabric=fabric,
                              clock=clock.now_s,
                              snapshot_every=sc.snapshot_every)
    store_audit = AuditLog("store", now=clock.now_s)
    leader.audit = store_audit
    standby.audit = store_audit
    assert leader.acquire_leadership() == 1

    # Synthetic uptime: a long committed history BEFORE the flood, so
    # the failover replay cost is judged against real log length (the
    # O(tail) ratchet — without compaction this would all replay).
    for i in range(sc.preload_txns):
        with leader.txn() as txn:
            txn.put_json("serve:preload", {"i": i})

    # No "errors" key: the sim data plane (admit → fixed-latency
    # completion) has no error path by construction, so an error count
    # would gate nothing — the zero-system-errors invariant is the LIVE
    # arm's to prove; the sim arms prove completed == admitted.
    counts = {"arrivals": 0, "admitted": 0, "rejected": 0,
              "completed": 0}
    story: Dict[str, Any] = {
        "leader": "ctl-A", "epoch": 1, "first_epoch": 1,
        "failovers": [], "heartbeats": {"ctl-A": 0, "ctl-B": 0},
        "stale_write_rejected": False, "stale_error": "",
        "split_brain_commits": 0, "max_over_admitted": 0.0,
    }
    had_led = {"ctl-A": True, "ctl-B": False}
    fenced = {"ctl-A": False, "ctl-B": False}

    # --- data plane (unaffected by control partitions by design) ---------
    def arrival(session: int, tenant: int) -> None:
        counts["arrivals"] += 1
        _, ok, _retry = fd.admit(
            DEPLOYMENT, payload={"session_id": f"s{session}"},
            tenant=f"t{tenant}",
        )
        if not ok:
            counts["rejected"] += 1
            return
        counts["admitted"] += 1
        loop.schedule_in(20.0, lambda: counts.__setitem__(
            "completed", counts["completed"] + 1))

    t_ms = 0.0
    horizon_ms = sc.duration_s * 1000.0
    end_ms = horizon_ms + sc.drain_s * 1000.0
    while True:
        t_ms += rng.expovariate(sc.offered_rps) * 1000.0
        if t_ms >= horizon_ms:
            break
        session = rng.randrange(sc.n_sessions)
        tenant = rng.randrange(sc.n_tenants)
        loop.schedule_at(t_ms, lambda s=session, t=tenant: arrival(s, t))

    # --- gossip (fabric-routed absorbs), through the drain ---------------
    def gossip() -> None:
        fd.gossip_round()
        if clock.now_ms() + sc.gossip_interval_s * 1000.0 <= end_ms:
            loop.schedule_in(sc.gossip_interval_s * 1000.0, gossip)

    loop.schedule_in(sc.gossip_interval_s * 1000.0, gossip)

    # --- control ticks ----------------------------------------------------
    def control_tick() -> None:
        now_s = clock.now_s()
        # 1. The instance that believes it leads heartbeats a txn; a
        #    failing renew demotes it, unreachable appends feed the
        #    bounded self-demotion window.
        active = next((s for s in (leader, standby) if s.is_leader()),
                      None)
        if active is not None and active.renew():
            try:
                with active.txn() as txn:
                    txn.put_json("serve:heartbeat", {
                        "owner": active.owner,
                        "tick": story["heartbeats"][active.owner] + 1,
                    })
                story["heartbeats"][active.owner] += 1
            except FabricUnreachable:
                pass  # the store tracked it (self-demotion window)
            except StaleEpochError:
                fenced[active.owner] = True
        # 2. Non-leaders run for the lease (standby first — it is the
        #    one on the log's side of every partition in the matrix).
        for cand in (standby, leader):
            if fenced[cand.owner] or cand.is_leader():
                continue
            try:
                epoch = cand.acquire_leadership()
            except FabricUnreachable:
                continue  # cut off from the log: no candidacy
            if epoch is None:
                # Another owner's lease is live. For an instance that
                # HAS led, that is the fence (a successor exists); a
                # standby that never led just keeps waiting.
                if had_led[cand.owner] and not cand.is_leader():
                    fenced[cand.owner] = True
                continue
            had_led[cand.owner] = True
            if cand.owner != story["leader"] or epoch != story["epoch"]:
                story["failovers"].append({
                    "at_s": round(now_s, 3), "owner": cand.owner,
                    "epoch": epoch,
                    "snapshot_index":
                        cand.last_recovery["snapshot_index"],
                    "tail_replayed":
                        cand.last_recovery["tail_replayed"],
                })
            story["leader"] = cand.owner
            story["epoch"] = epoch
        # 3. Zero-split-brain probe: once a successor leads and the
        #    partition healed, the deposed epoch wakes up and tries to
        #    finish a half-done write — it MUST bounce off the fence.
        if (story["leader"] != "ctl-A"
                and not story["stale_write_rejected"]
                and not fabric.partition_active()):
            try:
                fabric.call(
                    "store.append", log.append, story["first_epoch"],
                    [("put", "serve:half-done", "stale")],
                    src="ctl-A", dst="log",
                )
                story["split_brain_commits"] += 1
            except StaleEpochError as e:
                story["stale_write_rejected"] = True
                story["stale_error"] = str(e)[:80]
            except FabricUnreachable:
                pass
        # 4. Over-admission time series against the central oracle.
        budget = fd.budget(DEPLOYMENT)
        if budget is not None:
            over = fd.true_admitted(DEPLOYMENT) - budget.allowed(now_s)
            story["max_over_admitted"] = max(story["max_over_admitted"],
                                             round(over, 3))
        if clock.now_ms() + sc.control_interval_s * 1000.0 <= end_ms:
            loop.schedule_in(sc.control_interval_s * 1000.0, control_tick)

    loop.schedule_in(sc.control_interval_s * 1000.0, control_tick)

    # Drift audited AT the flood horizon (the allowance line keeps
    # growing after arrivals stop), then the drain window lets
    # completions land, post-heal gossip re-converge, and the fence
    # probe fire.
    loop.run_until(horizon_ms)
    drift = fd.drift_audit(DEPLOYMENT)
    loop.run_until(end_ms)

    # --- end-state convergence check --------------------------------------
    true_admitted = fd.true_admitted(DEPLOYMENT)
    now_s = clock.now_s()
    ledgers: Dict[str, Any] = {}
    reconverged = True
    for sid in sorted(fd.shards):
        ledger = fd.shards[sid].ledger(DEPLOYMENT)
        ledger.check(now_s)  # refresh the degraded flag post-heal
        ledgers[sid] = {
            "own": ledger.own_count,
            "merged": ledger.merged_count(),
            "degraded_entries": ledger.degraded_entries,
            "stale_at_end": ledger.stale(now_s),
        }
        if ledger.merged_count() != true_admitted or ledger.stale(now_s):
            reconverged = False

    demote_audits = [a for a in store_audit.to_dicts()
                     if a["trigger"] == "store_unreachable"]
    return {
        "scenario": {k: v for k, v in vars(sc).items()},
        "counts": counts,
        "drift": drift,
        "max_over_admitted": story["max_over_admitted"],
        "degrade_bound": round(
            (sc.n_shards - 1) * sc.rate_rps * sc.staleness_bound_s
            + sc.n_shards, 3),
        "frontdoor": fd.stats(),
        "store": {
            "leader": story["leader"],
            "epoch": story["epoch"],
            "failovers": story["failovers"],
            "heartbeats": story["heartbeats"],
            "self_demotions": {"ctl-A": leader.self_demotions,
                               "ctl-B": standby.self_demotions},
            "demote_audits": len(demote_audits),
            "stale_write_rejected": story["stale_write_rejected"],
            "stale_error": story["stale_error"],
            "split_brain_commits": story["split_brain_commits"],
            "rejected_appends": log.rejected_appends,
            "fence_epoch": log.fence_epoch,
            "appended_total": log.appended_total,
            "log_tail_records": len(log),
            "max_tail_replayed": max(leader.max_tail_replayed,
                                     standby.max_tail_replayed),
            "snapshots_taken": (leader.snapshots_taken
                                + standby.snapshots_taken),
        },
        "ledgers": ledgers,
        "reconverged": reconverged,
        "true_admitted": true_admitted,
        "fabric": fabric.stats(),
    }
