"""Sim twin of the distributed control plane (ISSUE 11).

The million-user scenarios only run here: this module drives the REAL
control-plane classes — :class:`~ray_dynamic_batching_tpu.serve.
frontdoor.FrontDoor` (shard ring + gossip ledgers), :class:`~ray_dynamic_
batching_tpu.serve.store.ReplicatedStore`/:class:`StoreLog`/:class:`Leader
Lease` (epoch-fenced failover), and :class:`~ray_dynamic_batching_tpu.
serve.router.PrefixDigestDirectory` (cluster-wide prefix routing) — on
the virtual clock, so shard gossip, store failover, and digest routing
are deterministic events and two same-seed runs render byte-identical
reports.

One run plays THREE sub-twins over one seeded flood:

- **gossip budget**: arrivals admit through the sharded front door while
  gossip rounds fire on the virtual clock; the report carries the drift
  audit (fleet admissions vs the central oracle, bounded by
  ``(N-1) * rate * staleness``).
- **store failover**: a leader controller heartbeats transactions into
  the shared log until it is killed mid-flood; the standby acquires the
  lease when it lapses (epoch bump, log fence) and the deposed leader's
  next write is REJECTED — the report pins the epoch numbers and the
  :class:`StaleEpochError`.
- **digest routing**: admitted requests route over model replicas whose
  prefix caches publish digest chains into a real
  ``PrefixDigestDirectory``; the same workload replays with digest
  routing OFF (pure pow-2) as the per-replica baseline arm, so the
  cluster-hit-rate-beats-baseline claim is measured, not assumed.

The gate (tools/run_frontdoor_soak.py --sim) asserts determinism,
accounting conservation, budget conservation within the staleness
bound, the epoch-fenced failover, and the hit-rate win.
"""

from __future__ import annotations

import collections
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ray_dynamic_batching_tpu.serve.frontdoor import FrontDoor
from ray_dynamic_batching_tpu.serve.router import PrefixDigestDirectory
from ray_dynamic_batching_tpu.serve.store import (
    LeaderLease,
    ReplicatedStore,
    StaleEpochError,
    StoreLog,
)
from ray_dynamic_batching_tpu.sim.clock import EventLoop, VirtualClock

DEPLOYMENT = "llm"


@dataclass
class FrontDoorScenario:
    """Deterministic control-plane flood: parameters shared by the CI
    smoke (tools/frontdoor_smoke.json canon) and ad-hoc what-ifs."""

    seed: int = 0
    duration_s: float = 30.0
    # Front door: 4 shards, global budget 200 rps under a 400 rps flood
    # (2x over-subscribed — the budget must bind).
    n_shards: int = 4
    rate_rps: float = 200.0
    burst: float = 200.0
    offered_rps: float = 400.0
    gossip_interval_s: float = 0.5
    # Store failover: leader killed mid-flood; standby takes over when
    # the lease lapses.
    control_interval_s: float = 0.5
    lease_duration_s: float = 2.0
    kill_leader_at_s: float = 12.0
    # Digest routing: model replicas with bounded prefix caches serving
    # a prompt-family mix (a few hot system prompts + cold tails).
    n_replicas: int = 3
    n_families: int = 6
    family_chain_pages: int = 3
    replica_cache_entries: int = 4
    n_sessions: int = 40
    hot_family_bias: float = 0.7  # fraction of traffic on 2 hot families


class _ModelReplica:
    """Digest-routing model replica: a bounded LRU of digest-chain keys
    standing in for the paged prefix cache, plus a busy counter standing
    in for queue depth."""

    def __init__(self, rid: str, cache_entries: int) -> None:
        self.rid = rid
        self.cache: "collections.OrderedDict" = collections.OrderedDict()
        self.cache_entries = cache_entries
        self.busy = 0
        self.completed = 0
        self.hits = 0
        self.misses = 0

    def digests(self) -> Dict[str, int]:
        return {key: level for key, level in self.cache.items()}

    def serve(self, chain: List[str]) -> bool:
        """True on a prefix hit (deepest chain key cached)."""
        hit = any(key in self.cache for key in reversed(chain))
        if hit:
            self.hits += 1
            deepest = next(k for k in reversed(chain) if k in self.cache)
            self.cache.move_to_end(deepest)
        else:
            self.misses += 1
        # Serving publishes the full chain (the admission inserts every
        # full-page prefix, exactly like PagedPrefixCache.insert).
        for level, key in enumerate(chain, start=1):
            if key not in self.cache:
                self.cache[key] = level
        while len(self.cache) > self.cache_entries:
            self.cache.popitem(last=False)
        return hit


def _family_chain(family: int, pages: int) -> List[str]:
    """Synthetic digest chain for a prompt family — stable strings play
    the role of the blake2b level keys (the directory treats keys as
    opaque)."""
    return [f"fam{family}:{level}" for level in range(1, pages + 1)]


def _run_arm(sc: FrontDoorScenario, digest_routing: bool) -> Dict[str, Any]:
    """One full deterministic run; the baseline arm re-runs the same
    seed with digest routing disabled."""
    clock = VirtualClock()
    loop = EventLoop(clock)
    rng = random.Random(sc.seed)

    # --- front door (real classes, virtual clock) -----------------------
    fd = FrontDoor(n_shards=sc.n_shards, clock=clock.now_s,
                   gossip_interval_s=sc.gossip_interval_s)
    fd.configure(DEPLOYMENT, sc.rate_rps, sc.burst)

    # --- replicated store (real classes, virtual clock) -----------------
    log = StoreLog(now=clock.now_s)
    lease = LeaderLease(sc.lease_duration_s, clock=clock.now_s)
    leader = ReplicatedStore(log, lease, "ctl-A")
    assert leader.acquire_leadership() == 1
    standby = ReplicatedStore(log, lease, "ctl-B")
    store_state: Dict[str, Any] = {
        "leader": "ctl-A", "epoch": 1, "failover_at_s": None,
        "stale_write_rejected": False, "stale_error": "",
        "heartbeats": {"ctl-A": 0, "ctl-B": 0},
        "completions_while_leaderless": 0,
    }

    # --- digest-routing data plane --------------------------------------
    replicas = {f"r{i}": _ModelReplica(f"r{i}", sc.replica_cache_entries)
                for i in range(sc.n_replicas)}
    directory = PrefixDigestDirectory()
    counts = {"arrivals": 0, "admitted": 0, "rejected": 0, "completed": 0,
              "errors": 0}

    def route(chain: List[str]) -> _ModelReplica:
        ids = sorted(replicas)
        if digest_routing and chain:
            depth, holders = directory.best(chain, ids)
            if depth > 0:
                ids = sorted(holders)
        if len(ids) == 1:
            return replicas[ids[0]]
        a, b = rng.sample(ids, 2)
        return replicas[a if replicas[a].busy <= replicas[b].busy else b]

    def service_time(hit: bool, chain: List[str]) -> float:
        # Prefill dominates cold admissions; a prefix hit skips it.
        return 0.01 + (0.0 if hit else 0.01 * len(chain))

    def arrival(session: int, family: int) -> None:
        counts["arrivals"] += 1
        payload = {"session_id": f"s{session}"}
        _, ok, _retry = fd.admit(DEPLOYMENT, payload=payload,
                                 tenant=f"t{session % 4}")
        if not ok:
            counts["rejected"] += 1
            return
        counts["admitted"] += 1
        chain = _family_chain(family, sc.family_chain_pages)
        replica = route(chain)
        hit = replica.serve(chain)
        replica.busy += 1

        def complete(r=replica) -> None:
            r.busy -= 1
            r.completed += 1
            counts["completed"] += 1
            if store_state["leader"] is None:
                store_state["completions_while_leaderless"] += 1

        loop.schedule_in(service_time(hit, chain) * 1000.0, complete)

    # Seeded arrival schedule (exponential gaps), fixed up front so both
    # arms replay the identical offered load.
    t_ms = 0.0
    horizon_ms = sc.duration_s * 1000.0
    hot = (0, 1)
    while True:
        t_ms += rng.expovariate(sc.offered_rps) * 1000.0
        if t_ms >= horizon_ms:
            break
        session = rng.randrange(sc.n_sessions)
        if rng.random() < sc.hot_family_bias:
            family = hot[rng.randrange(len(hot))]
        else:
            family = 2 + rng.randrange(sc.n_families - 2)
        loop.schedule_at(t_ms, lambda s=session, f=family: arrival(s, f))

    # Gossip rounds on the virtual clock.
    def gossip() -> None:
        fd.gossip_round()
        if clock.now_ms() + sc.gossip_interval_s * 1000.0 < horizon_ms:
            loop.schedule_in(sc.gossip_interval_s * 1000.0, gossip)

    loop.schedule_in(sc.gossip_interval_s * 1000.0, gossip)

    # Control ticks: the live leader heartbeats a transaction; the
    # standby replays the log and takes over once the lease lapses.
    # Digest publications ride the control tick, like the live
    # controller's _publish_prefix_digests.
    def control_tick() -> None:
        now_s = clock.now_s()
        if store_state["leader"] == "ctl-A" \
                and now_s >= sc.kill_leader_at_s:
            store_state["leader"] = None  # killed: stops renewing
        active = {"ctl-A": leader, "ctl-B": standby}.get(
            store_state["leader"] or ""
        )
        if active is not None and active.renew():
            with active.txn() as txn:
                txn.put_json("serve:heartbeat", {
                    "owner": active.owner,
                    "tick": store_state["heartbeats"][active.owner] + 1,
                })
            store_state["heartbeats"][active.owner] += 1
        elif store_state["leader"] is None:
            epoch = standby.acquire_leadership()
            if epoch is not None:
                store_state["leader"] = "ctl-B"
                store_state["epoch"] = epoch
                store_state["failover_at_s"] = round(now_s, 3)
                # The deposed leader wakes up and tries to finish a
                # half-done write: the fence must reject it.
                try:
                    with leader.txn() as txn:
                        txn.put_json("serve:heartbeat",
                                     {"owner": "ctl-A", "tick": -1})
                except StaleEpochError as e:
                    store_state["stale_write_rejected"] = True
                    store_state["stale_error"] = str(e)
        for rid in sorted(replicas):
            directory.publish(rid, 128, replicas[rid].digests())
        if clock.now_ms() + sc.control_interval_s * 1000.0 < horizon_ms:
            loop.schedule_in(sc.control_interval_s * 1000.0, control_tick)

    loop.schedule_in(sc.control_interval_s * 1000.0, control_tick)

    # Drift audited AT the flood horizon (the allowance line keeps
    # growing while arrivals have stopped — auditing later would read
    # artificially under-admitted), then drain so in-flight completions
    # land.
    loop.run_until(horizon_ms)
    drift = fd.drift_audit(DEPLOYMENT)
    loop.run_until(horizon_ms + 5_000.0)
    hits = sum(r.hits for r in replicas.values())
    misses = sum(r.misses for r in replicas.values())
    return {
        "digest_routing": digest_routing,
        "counts": counts,
        "drift": drift,
        "frontdoor": fd.stats(),
        "store": {
            **{k: v for k, v in store_state.items()
               if k != "stale_error"},
            "stale_error": store_state["stale_error"][:80],
            "log_records": len(log),
            "rejected_appends": log.rejected_appends,
            "fence_epoch": log.fence_epoch,
        },
        "routing": {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / max(1, hits + misses), 4),
            "per_replica": {
                rid: {"completed": r.completed, "hits": r.hits,
                      "misses": r.misses}
                for rid, r in sorted(replicas.items())
            },
            "directory_publishes": directory.snapshot()["publishes"],
        },
    }


def run_frontdoor_sim(
    scenario: Optional[FrontDoorScenario] = None,
) -> Dict[str, Any]:
    """Both arms (digest routing on / off) over the identical seeded
    flood; the gate compares their hit rates and checks every
    conservation invariant on the routed arm."""
    sc = scenario or FrontDoorScenario()
    return {
        "scenario": vars(sc),
        "routed": _run_arm(sc, digest_routing=True),
        "baseline": _run_arm(sc, digest_routing=False),
    }
