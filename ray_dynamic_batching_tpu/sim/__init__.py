"""sim/ — deterministic discrete-event what-if simulator for the SLO
scheduler.

Replays a workload (synthetic ``RatePattern``s, a recorded arrivals
JSONL, or arrivals reconstructed from a flight-recorder span dump)
against the REAL planners (``scheduler/nexus.py`` +
``scheduler/replan.decide_replan``) at a virtual clock, with the
committed profile tables as the execution cost model. Answers "would
this plan hold at 2x traffic?" / "can we drop a chip?" in milliseconds
of wall time, byte-deterministically. CLI: ``tools/run_sim.py``.
"""

from ray_dynamic_batching_tpu.sim.clock import EventLoop, VirtualClock
from ray_dynamic_batching_tpu.sim.control import SimScheduler
from ray_dynamic_batching_tpu.sim.engine import SimEngine
from ray_dynamic_batching_tpu.sim.queue import (
    SimQueueManager,
    SimRequest,
    SimRequestQueue,
)
from ray_dynamic_batching_tpu.sim.report import (
    compare_reports,
    format_compare,
    format_gray_timeline,
    gray_timeline,
    hop_drift_report,
    merged_hop_sketches,
    render_json,
    slo_attainment,
)
from ray_dynamic_batching_tpu.sim.simulator import (
    AcceptanceCollapse,
    EngineDegradation,
    EngineFailure,
    Scenario,
    SimModelSpec,
    Simulation,
)
from ray_dynamic_batching_tpu.sim.workload import (
    arrivals_from_spans,
    load_recorded_arrivals,
    merge_arrivals,
    scale_arrivals,
    synthetic_arrivals,
)

__all__ = [
    "EventLoop",
    "VirtualClock",
    "SimScheduler",
    "SimEngine",
    "SimQueueManager",
    "SimRequest",
    "SimRequestQueue",
    "compare_reports",
    "format_compare",
    "format_gray_timeline",
    "gray_timeline",
    "hop_drift_report",
    "merged_hop_sketches",
    "render_json",
    "slo_attainment",
    "AcceptanceCollapse",
    "EngineDegradation",
    "EngineFailure",
    "Scenario",
    "SimModelSpec",
    "Simulation",
    "arrivals_from_spans",
    "load_recorded_arrivals",
    "merge_arrivals",
    "scale_arrivals",
    "synthetic_arrivals",
]
