"""Simulated per-model queues — the live queue's semantics at virtual time.

Mirrors ``engine/queue.py`` (``RequestQueue``/``QueueManager``) exactly
where the scheduler can observe behavior, against the injected
:class:`~ray_dynamic_batching_tpu.sim.clock.VirtualClock`:

- bounded add with class-aware shed-when-full (best-effort displaced
  first; equal class drops the newcomer — ref scheduler.py:238-254);
- batch pop ordered class-then-deadline with the SAME pinned
  anti-starvation stride as live (the ordering core,
  ``engine/queue.ClassBuckets``, is imported, not re-expressed — the two
  sides cannot drift);
- stale discard at profiled latency (``deadline < now + expected_latency``
  — the staleness rule, ref :281-283);
- per-request SLO-violation accounting on completion (ref :324-341) and
  latency percentiles (exact over ALL completions here — a simulation
  report wants the whole run, not a rolling window), sliced per QoS class.

No threads, no locks, no futures: the event loop serializes everything,
and a completed request is just a counted outcome. ``stats()`` /
``class_stats()`` return the same keys as the live queue so report code
reads either side.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_dynamic_batching_tpu.engine.queue import ClassBuckets, ClassCounters
from ray_dynamic_batching_tpu.engine.request import (
    DEFAULT_QOS_CLASS,
    DEFAULT_TENANT,
)
from ray_dynamic_batching_tpu.sim.clock import VirtualClock
from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch

# The simulator's slice of the live hop taxonomy (utils/hops.HOP_ORDER):
# the sim has no proxy/handle/router front end — a request exists from
# its (virtual) enqueue — so exactly two hops are observable, and the
# sim<->live drift report compares exactly these.
SIM_HOPS = ("queue.wait", "engine.step")

SLO_WINDOW = 200  # live parity: recent-completion compliance window


@dataclass
class SimRequest:
    """The simulator's request: arrival + contract, nothing else."""

    model: str
    arrival_ms: float
    slo_ms: float
    seq_len: int = 0
    qos_class: str = DEFAULT_QOS_CLASS
    tenant: str = DEFAULT_TENANT
    # Stamped at dequeue: the boundary between the sim's two ledger hops
    # (queue.wait = arrival -> pop, engine.step = pop -> completion).
    popped_ms: Optional[float] = None
    # Prefill cost BEYOND the profile-row step (ISSUE 15): > 0 marks a
    # long-prompt request whose prefill the engine executes either
    # inside its turn (mono — head-of-line blocking) or as budgeted
    # chunk events between turns (chunked). 0.0 = a bucketed prompt
    # whose prefill the row already covers.
    prefill_ms: float = 0.0
    # Client-retry generation (ISSUE 19): 0 = first attempt; a stale-shed
    # request resubmitted by the retry model arrives again with this
    # bumped — the amplification axis the retry budget bounds.
    retry_attempt: int = 0
    # Non-None marks a query of death (ISSUE 19): executing a batch that
    # contains it fails the batch, and isolation costs the engine
    # ceil(log2(B)) bisection probes plus a rescue pass.
    poison_id: Optional[str] = None

    @property
    def deadline_ms(self) -> float:
        return self.arrival_ms + self.slo_ms


def percentile(samples: List[float], p: float) -> float:
    """The live ``RollingWindow.percentile`` rule (nearest-rank via
    ceil), over an explicit sample list."""
    if not samples:
        return 0.0
    data = sorted(samples)
    idx = min(len(data) - 1, max(0, math.ceil(p * len(data)) - 1))
    return data[idx]


class SimRequestQueue:
    """Bounded class-then-deadline queue for one model, advanced by the
    event loop."""

    def __init__(self, model: str, clock: VirtualClock,
                 max_len: int = 4096) -> None:
        self.model = model
        self.clock = clock
        self.max_len = max_len
        self._buckets = ClassBuckets()
        # Optional decision ring (wired to the SimScheduler's AuditLog so
        # class-aware displacement sheds land in the same timeline live
        # queues feed).
        self.audit = None
        # Optional stale-shed hook (ISSUE 19): called with (queue, req)
        # when get_batch discards a request past its deadline. The
        # scheduler's client-retry model hangs off this; None (default)
        # is byte-identical to the pre-retry simulator.
        self.on_stale = None
        # --- stats (same counters as engine/queue.py) ---
        self.latency_samples: List[float] = []
        self._recent_outcomes: List[bool] = []
        self.total_enqueued = 0
        self.total_dropped = 0
        self.total_stale = 0
        self.total_completed = 0
        self.total_violations = 0
        self.total_poisoned = 0
        # Shared per-class accounting (engine/queue.ClassCounters — the
        # live queue's implementation, imported like ClassBuckets).
        self._classes = ClassCounters()
        # Per-hop latency sketches (virtual-event hop ledger): the SAME
        # sketch + hop names the live decomposer aggregates with, so the
        # sim<->live hop-drift report compares like with like.
        self.hop_sketches: Dict[str, QuantileSketch] = {
            hop: QuantileSketch() for hop in SIM_HOPS
        }

    def _cls(self, qos: str) -> Dict[str, float]:
        return self._classes.cls(qos)

    # --- producer side ----------------------------------------------------
    def add_request(self, request: SimRequest) -> bool:
        if len(self._buckets) >= self.max_len:
            victim = self._buckets.shed_victim(request)
            if victim is None:
                self.total_dropped += 1
                c = self._cls(request.qos_class)
                # Per-class "enqueued" counts offered-at-door (live queue
                # rule) so conservation holds through door-drops too.
                c["enqueued"] += 1
                c["dropped"] += 1
                return False
            self.total_dropped += 1
            self._cls(victim.qos_class)["dropped"] += 1
            if self.audit is not None:
                self.audit.record(
                    "qos_shed",
                    key=self.model,
                    observed={"victim_qos": victim.qos_class,
                              "victim_tenant": victim.tenant,
                              "for_qos": request.qos_class},
                    diff={"displaced": victim.qos_class},
                    note="full queue: lowest-class latest-deadline "
                         "displaced",
                )
        self._buckets.push(request)
        self.total_enqueued += 1
        self._cls(request.qos_class)["enqueued"] += 1
        return True

    # --- consumer side ----------------------------------------------------
    def get_batch(
        self,
        batch_size: int,
        expected_latency_ms: float = 0.0,
        discard_stale: bool = True,
    ) -> List[SimRequest]:
        """Pop up to ``batch_size`` in one sweep at the CURRENT virtual
        time — class then deadline, live anti-starvation stride —
        discarding requests that cannot meet their deadline given the
        profiled batch latency (live ``get_batch`` rule)."""
        now = self.clock.now_ms()
        out: List[SimRequest] = []
        while len(self._buckets) and len(out) < batch_size:
            req = self._buckets.pop()
            if discard_stale and req.deadline_ms < now + expected_latency_ms:
                self.total_stale += 1
                self._cls(req.qos_class)["stale"] += 1
                if self.on_stale is not None:
                    self.on_stale(self, req)
                continue
            req.popped_ms = now
            out.append(req)
        return out

    def __len__(self) -> int:
        return len(self._buckets)

    # --- accounting (live record_batch_completion) ------------------------
    def record_batch_completion(
        self, batch: List[SimRequest], completed_at_ms: float
    ) -> int:
        violations = 0
        for req in batch:
            total_ms = completed_at_ms - req.arrival_ms
            ok = total_ms <= req.slo_ms
            violations += 0 if ok else 1
            self.latency_samples.append(total_ms)
            # Virtual-event hop ledger: arrival -> pop -> completion
            # tiles the request's whole sim lifetime (residual == 0 by
            # construction — the sim has no instrumentation gaps).
            popped = req.popped_ms if req.popped_ms is not None \
                else completed_at_ms
            self.hop_sketches["queue.wait"].observe(
                max(0.0, popped - req.arrival_ms)
            )
            self.hop_sketches["engine.step"].observe(
                max(0.0, completed_at_ms - popped)
            )
            self._recent_outcomes.append(ok)
            c = self._cls(req.qos_class)
            c["completed"] += 1
            c["violations"] += 0 if ok else 1
        if len(self._recent_outcomes) > SLO_WINDOW:
            del self._recent_outcomes[:-SLO_WINDOW]
        self.total_completed += len(batch)
        self.total_violations += violations
        return violations

    def count_poisoned(self, req: SimRequest) -> None:
        """A popped query of death condemned by engine-side bisection
        (ISSUE 19): terminally rejected, never completed, never retried —
        accounted as a drop (it missed its SLO as surely as a displaced
        request) plus its own counter so the report can tell poison
        verdicts from load shedding. Conservation holds: arrivals ==
        completed + stale + dropped + pending."""
        self.total_dropped += 1
        self.total_poisoned += 1
        self._cls(req.qos_class)["dropped"] += 1

    def count_backlog_stale(self, req: SimRequest) -> None:
        """A popped request shed OUTSIDE the queue (the chunked-prefill
        backlog's deadline economics, ISSUE 15): its train's remaining
        chunks would land past the deadline, so the engine discards it
        exactly like the queue's own stale rule — and it must stay
        accounted (arrivals == completed + stale + dropped + pending),
        the live ``count_external_drop`` contract."""
        self.total_stale += 1
        self._cls(req.qos_class)["stale"] += 1

    def slo_compliance(self) -> float:
        if not self._recent_outcomes:
            return 1.0
        return sum(self._recent_outcomes) / len(self._recent_outcomes)

    def stats(self) -> Dict[str, float]:
        return {
            "depth": float(len(self)),
            "enqueued": float(self.total_enqueued),
            "dropped": float(self.total_dropped),
            "stale": float(self.total_stale),
            "completed": float(self.total_completed),
            "violations": float(self.total_violations),
            "slo_compliance": self.slo_compliance(),
            "latency_p50_ms": percentile(self.latency_samples, 0.50),
            "latency_p95_ms": percentile(self.latency_samples, 0.95),
            "latency_p99_ms": percentile(self.latency_samples, 0.99),
            # Live records queue delay at completion via
            # queue_delay_ms(t) = t - arrival — numerically the same
            # series as total latency, so derive rather than duplicate.
            "queue_delay_p95_ms": percentile(self.latency_samples, 0.95),
        }

    def class_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-class counter slices + live depth (live queue key set)."""
        return self._classes.stats(self._buckets.depth_by_class())

    def hop_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-hop {count, p50_ms, p95_ms} from the virtual-event
        ledger (report surface; the raw sketches stay mergeable)."""
        return {
            hop: sk.summary(quantiles=(0.5, 0.95))
            for hop, sk in self.hop_sketches.items()
        }


class SimQueueManager:
    """Name → queue registry (live ``QueueManager`` shape)."""

    def __init__(self, clock: VirtualClock, max_len: int = 4096) -> None:
        self.clock = clock
        self.max_len = max_len
        # Shared decision ring handed to every queue created from here
        # (set by the simulation before traffic starts).
        self.audit = None
        # Shared stale-shed hook, likewise handed to every queue (set by
        # the scheduler when the client-retry model is enabled).
        self.on_stale = None
        self._queues: Dict[str, SimRequestQueue] = {}

    def queue(self, model: str) -> SimRequestQueue:
        if model not in self._queues:
            q = SimRequestQueue(model, self.clock, self.max_len)
            q.audit = self.audit
            q.on_stale = self.on_stale
            self._queues[model] = q
        return self._queues[model]

    def queues(self) -> Dict[str, SimRequestQueue]:
        return dict(self._queues)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {m: q.stats() for m, q in self._queues.items()}
