"""Workload sources for the simulator — three roads into one arrival list.

An arrival is ``(t_s, model)``: offset seconds from run start. Sources:

1. **synthetic** — the live load generator's own ``RatePattern`` +
   ``arrival_times`` (``engine/workload.py``), which are already pure
   and seeded; the simulator replays exactly the offsets a threaded
   ``WorkloadDriver`` with the same (pattern, seed) would submit at.
2. **recorded** — the JSONL a ``WorkloadDriver(record_path=...)`` (or
   ``tools/run_slo_demo.py``) wrote: ``{"t_s": ..., "model": ...}`` per
   line. Any demo/live run that recorded arrivals is replayable.
3. **flight-recorder spans** — a PR-1 ``spans.jsonl`` dump: every
   request's ``queue.wait`` span starts at its enqueue, tagged with the
   model, so a trace capture IS an arrival log (offsets re-anchored to
   the earliest span).

``scale_arrivals`` answers "at 2x traffic?": integer part replicates
each arrival (tiny deterministic stagger so copies are distinct
queue entries), fractional part admits by seeded coin-flip.
"""

from __future__ import annotations

import json
import random
from typing import Dict, Iterable, List, Tuple

from ray_dynamic_batching_tpu.engine.request import (
    DEFAULT_QOS_CLASS,
    QOS_RANK,
)
from ray_dynamic_batching_tpu.engine.workload import (
    RatePattern,
    arrival_times,
)

Arrival = Tuple[float, str]  # (offset seconds, model)
# Class-tagged arrival: (offset seconds, model, qos_class). Workload
# plumbing accepts either shape — untagged arrivals serve at the default
# class — so pre-QoS recordings stay replayable.
ClassArrival = Tuple[float, str, str]


def draw_qos(rng: random.Random, class_mix: Dict[str, float]) -> str:
    """One seeded weighted class draw from a mix — THE tagging primitive
    shared by :func:`assign_qos_classes` and the simulator's per-model
    streams (one implementation, no drift). An empty mix is the default
    class; unknown classes and non-positive totals are rejected loudly
    (a silently-mistagged what-if is a confidently wrong one)."""
    if not class_mix:
        return DEFAULT_QOS_CLASS
    unknown = set(class_mix) - set(QOS_RANK)
    if unknown:
        raise ValueError(
            f"unknown qos class(es) in mix: {sorted(unknown)} "
            f"(known: {sorted(QOS_RANK)})"
        )
    classes = sorted(class_mix)  # deterministic draw order
    total = sum(class_mix[c] for c in classes)
    if total <= 0:
        raise ValueError("class_mix fractions must sum > 0")
    x = rng.random() * total
    acc = 0.0
    for c in classes:
        acc += class_mix[c]
        if x < acc:
            return c
    return classes[-1]


def assign_qos_classes(
    arrivals: List[Arrival],
    class_mix: Dict[str, float],
    seed: int = 0,
) -> List[ClassArrival]:
    """Tag each arrival with a QoS class drawn from ``class_mix``
    (fractions, normalized) by seeded draw — same trace + mix + seed =>
    byte-identical tags. An empty mix tags everything default-class."""
    rng = random.Random(seed)
    return [(t, m, draw_qos(rng, class_mix)) for t, m in arrivals]


def synthetic_arrivals(
    model: str,
    pattern: RatePattern,
    duration_s: float,
    poisson: bool = False,
    seed: int = 0,
) -> List[Arrival]:
    return [
        (t, model)
        for t in arrival_times(pattern, duration_s, poisson=poisson,
                               seed=seed)
    ]


def merge_arrivals(streams: Iterable[List]) -> List:
    """One time-ordered list; ties keep stream order (stable sort) so
    the event sequence is canonical. Accepts plain or class-tagged
    arrivals (mixing is fine — the consumer defaults untagged ones)."""
    out: List = []
    for s in streams:
        out.extend(s)
    out.sort(key=lambda a: a[0])
    return out


def load_recorded_arrivals(path: str) -> List[Arrival]:
    """Parse a ``WorkloadDriver(record_path=...)`` JSONL."""
    out: List[Arrival] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            out.append((float(rec["t_s"]), str(rec["model"])))
    out.sort(key=lambda a: a[0])
    return out


def arrivals_from_spans(path: str) -> List[Arrival]:
    """Reconstruct arrivals from a flight-recorder span JSONL: each
    ``queue.wait`` span starts at the request's enqueue and carries the
    model attribute. Offsets are re-anchored to the earliest such span.

    SURVIVOR BIAS caveat: ``queue.wait`` spans are recorded only for
    requests actually POPPED into a batch — requests the live run
    dropped at enqueue or stale-discarded left no such span, so a dump
    captured during overload under-counts offered load by exactly the
    shed fraction, and what-if conclusions replay optimistic. For
    overload studies prefer a ``WorkloadDriver(record_path=...)``
    recording, which logs every SUBMITTED arrival."""
    raw: List[Arrival] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            span = json.loads(line)
            if span.get("name") != "queue.wait":
                continue
            model = (span.get("attributes") or {}).get("model")
            if model is None:
                continue
            raw.append((float(span["start_ms"]) / 1000.0, str(model)))
    if not raw:
        return []
    t0 = min(t for t, _ in raw)
    out = [(t - t0, m) for t, m in raw]
    out.sort(key=lambda a: a[0])
    return out


def scale_arrivals(
    arrivals: List[Arrival], scale: float, seed: int = 0
) -> List[Arrival]:
    """What-if traffic scaling of a FIXED trace. ``scale=2.0`` doubles
    every arrival (copies staggered 0.1 ms apart so they are distinct
    queue entries at distinct instants); ``scale=1.5`` doubles half of
    them by seeded coin-flip; ``scale=0.5`` thins. Deterministic."""
    if scale == 1.0:
        return list(arrivals)
    if scale <= 0.0:
        return []
    rng = random.Random(seed)
    whole = int(scale)
    frac = scale - whole
    out: List = []
    for arrival in arrivals:
        t, rest = arrival[0], arrival[1:]
        copies = whole + (1 if rng.random() < frac else 0)
        for i in range(copies):
            out.append((t + i * 1e-4, *rest))
    out.sort(key=lambda a: a[0])
    return out
