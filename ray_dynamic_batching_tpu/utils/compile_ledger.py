"""Runtime compile flight recorder — the jit layer's hop ledger.

One silent mid-serving XLA recompile costs more than a thousand decode
turns, and nothing in the stack proved it never happens. This module
hooks ``jax.monitoring``'s compilation callbacks and attributes every
compile to the jit program that triggered it:

- ``instrument(name, fn)`` wraps a compiled callable; while a wrapped
  call is on the stack, any compile event that fires is charged to
  ``name``. A cached dispatch fires ZERO events, so the wrapper's
  steady-state cost is one thread-local push/pop. One wrapped call in
  which any event fired counts as ONE **compile episode** — jax emits
  several ``backend_compile`` bursts per trace (three on a first call,
  two on a retrace, measured), so raw events are the wrong unit.
- a phase machine (``startup`` → ``warmup`` → ``steady``) driven by
  ``begin_warmup()``/``end_warmup()`` around ``DecodeEngine.warmup()``
  (depth-counted: nested warmups — multi-engine processes — re-enter
  the warmup phase). The first ``end_warmup`` that unwinds to depth 0
  arms the **steady-state mark**: every later episode is a recorded
  violation carrying the function, argument shapes, and triggering
  callsite — a named guilty hop, never a mystery stall.
- every episode increments ``rdb_jit_compiles_total{fn,phase}`` (fn
  label bounded — an unbounded cardinality bug cannot mint series) and
  emits a ``jit.compile`` tracer span so recompiles join the PR-1
  flight record and the PR-8 hop ledger.

Compiles with no wrapped call on the stack land under
``__unattributed__`` with a callsite walked from the Python stack; for
those the episode unit degrades to one-per-``backend_compile``-burst
(there is no call boundary to coalesce on — documented, not hidden).

``tools/check_compiles.py`` is the CI gate over this ledger: warmup
plus a canonical serving segment must stay inside the ratcheted budget
(``tools/compile_budget.json``) with ZERO steady-phase episodes.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.concurrency import (
    OrderedLock,
    assert_owner,
)
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils.tracing import tracer

logger = get_logger("compile_ledger")

UNATTRIBUTED = "__unattributed__"

PHASE_STARTUP = "startup"
PHASE_WARMUP = "warmup"
PHASE_STEADY = "steady"

# Event names jax.monitoring emits per compilation stage (duration
# listeners). Any of them firing means real (re)compilation work — a
# cached dispatch emits none.
_EV_TRACE = "/jax/core/compile/jaxpr_trace_duration"
_EV_LOWER = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_EV_BACKEND = "/jax/core/compile/backend_compile_duration"

# Hot-path fn labels are a small closed set (ops/jit_model.py registry
# + __unattributed__); 16 leaves headroom without unbounding the series.
COMPILES = m.Counter(
    "rdb_jit_compiles_total",
    "XLA compile episodes by jit program and ledger phase "
    "(startup | warmup | steady — steady MUST stay 0 in serving)",
    tag_keys=("fn", "phase"),
    bounded_tags={"fn": 16},
)


class SteadyStateViolation(RuntimeError):
    """A compile landed after the steady-state mark (post-warmup)."""


_tls = threading.local()


class _Frame:
    __slots__ = ("name", "fired", "trace_ms", "lower_ms", "compile_ms")

    def __init__(self, name: str) -> None:
        self.name = name
        self.fired = False
        self.trace_ms = 0.0
        self.lower_ms = 0.0
        self.compile_ms = 0.0


def _frames() -> List[_Frame]:
    stack = getattr(_tls, "frames", None)
    if stack is None:
        stack = _tls.frames = []
    return stack


def _shape_sig(args: Tuple[Any, ...], limit: int = 12) -> str:
    """Compact shape/dtype signature of a call's positional args —
    attribution detail for episodes, computed ONLY when one fired."""
    parts: List[str] = []
    for a in args[:limit]:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        elif isinstance(a, (int, float, bool)):
            parts.append(repr(a))
        elif isinstance(a, (tuple, list)):
            parts.append(f"{type(a).__name__}({len(a)})")
        else:
            parts.append(type(a).__name__)
    if len(args) > limit:
        parts.append("...")
    return f"({', '.join(parts)})"


def _callsite() -> str:
    """First stack frame outside jax and this module — the code that
    triggered the compile, repo-relative when possible."""
    for fr in reversed(traceback.extract_stack()):
        fn = fr.filename.replace("\\", "/")
        if "/jax/" in fn or "/jaxlib/" in fn or fn.endswith(
            "/utils/compile_ledger.py"
        ):
            continue
        for marker in ("ray_dynamic_batching_tpu/", "tools/", "tests/"):
            i = fn.find(marker)
            if i >= 0:
                fn = fn[i:]
                break
        return f"{fn}:{fr.lineno} ({fr.name})"
    return "<unknown>"


class CompileLedger:
    """Process-wide compile episode recorder (see module docstring)."""

    def __init__(self) -> None:
        self._lock = OrderedLock("compile_ledger")
        self._phase = PHASE_STARTUP
        self._warmup_depth = 0
        self._armed = False  # a warmup has completed; next phase steady
        # fn -> {"episodes": int, "by_phase": {phase: int},
        #        "trace_ms"/"lower_ms"/"compile_ms": float}
        self._fns: Dict[str, Dict[str, Any]] = {}
        self._violations: List[Dict[str, Any]] = []

    # --- phase machine --------------------------------------------------
    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    def begin_warmup(self) -> None:
        with self._lock:
            self._warmup_depth += 1
            self._phase = PHASE_WARMUP

    def end_warmup(self) -> None:
        with self._lock:
            self._warmup_depth = max(0, self._warmup_depth - 1)
            if self._warmup_depth == 0:
                self._armed = True
                self._phase = PHASE_STEADY

    def steady_state(self) -> None:
        """Force-arm the steady-state mark (gates/tests; engine warmup
        arms it through ``end_warmup``)."""
        with self._lock:
            self._warmup_depth = 0
            self._armed = True
            self._phase = PHASE_STEADY

    # --- recording ------------------------------------------------------
    def _on_event(self, event: str, duration_ms: float) -> None:
        stack = _frames()
        if stack:
            fr = stack[-1]
            fr.fired = True
            if event == _EV_TRACE:
                fr.trace_ms += duration_ms
            elif event == _EV_LOWER:
                fr.lower_ms += duration_ms
            else:
                fr.compile_ms += duration_ms
            return
        # No wrapped call on this thread's stack: un-coalesced. Count
        # one episode per backend burst; fold trace/lower time into the
        # same bucket so the ms totals stay honest.
        if event == _EV_BACKEND:
            self._record(
                UNATTRIBUTED, shapes="", callsite=_callsite(),
                trace_ms=0.0, lower_ms=0.0, compile_ms=duration_ms,
            )
        else:
            with self._lock:
                rec = self._fn_rec(UNATTRIBUTED)
                key = "trace_ms" if event == _EV_TRACE else "lower_ms"
                rec[key] += duration_ms

    def _fn_rec(self, name: str) -> Dict[str, Any]:
        assert_owner(self._lock)
        rec = self._fns.get(name)
        if rec is None:
            rec = self._fns[name] = {
                "episodes": 0, "by_phase": {},
                "trace_ms": 0.0, "lower_ms": 0.0, "compile_ms": 0.0,
            }
        return rec

    def _record(self, name: str, shapes: str, callsite: str,
                trace_ms: float, lower_ms: float,
                compile_ms: float) -> None:
        end = time.monotonic() * 1000.0
        with self._lock:
            phase = self._phase
            rec = self._fn_rec(name)
            rec["episodes"] += 1
            rec["by_phase"][phase] = rec["by_phase"].get(phase, 0) + 1
            rec["trace_ms"] += trace_ms
            rec["lower_ms"] += lower_ms
            rec["compile_ms"] += compile_ms
            if phase == PHASE_STEADY:
                self._violations.append({
                    "fn": name, "phase": phase, "shapes": shapes,
                    "callsite": callsite,
                    "trace_ms": round(trace_ms, 3),
                    "lower_ms": round(lower_ms, 3),
                    "compile_ms": round(compile_ms, 3),
                })
        # Outside the ledger lock on purpose: the metric and tracer have
        # their own (metrics-rank / plain) locks and neither needs ours.
        COMPILES.inc(tags={"fn": name, "phase": phase})
        total = trace_ms + lower_ms + compile_ms
        tracer().record_span(
            "jit.compile",
            start_ms=end - total, end_ms=end,
            fn=name, phase=phase, shapes=shapes, callsite=callsite,
            trace_ms=round(trace_ms, 3), lower_ms=round(lower_ms, 3),
            compile_ms=round(compile_ms, 3),
        )
        if phase == PHASE_STEADY:
            logger.warning(
                "steady-state compile: fn=%s shapes=%s at %s "
                "(%.1f ms trace, %.1f ms lower, %.1f ms backend)",
                name, shapes, callsite, trace_ms, lower_ms, compile_ms,
            )

    # --- instrumentation ------------------------------------------------
    def instrument(self, name: str,
                   fn: Callable[..., Any]) -> Callable[..., Any]:
        """Wrap a compiled callable so its compiles are charged to
        ``name``. Cached dispatches cost one list push/pop."""
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            frame = _Frame(name)
            stack = _frames()
            stack.append(frame)
            try:
                return fn(*args, **kwargs)
            finally:
                stack.pop()
                if frame.fired:
                    self._record(
                        name,
                        shapes=_shape_sig(args),
                        callsite=_callsite(),
                        trace_ms=frame.trace_ms,
                        lower_ms=frame.lower_ms,
                        compile_ms=frame.compile_ms,
                    )
        wrapper.__name__ = f"ledger[{name}]"
        wrapper.__wrapped__ = fn
        return wrapper

    # --- inspection -----------------------------------------------------
    def counts(self, phase: Optional[str] = None) -> Dict[str, int]:
        with self._lock:
            if phase is None:
                return {n: r["episodes"] for n, r in self._fns.items()}
            return {
                n: r["by_phase"].get(phase, 0)
                for n, r in self._fns.items()
                if r["by_phase"].get(phase, 0)
            }

    def violations(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._violations)

    def check_steady(self) -> None:
        """Raise :class:`SteadyStateViolation` if any compile landed
        after the steady-state mark — the gate's hard failure."""
        v = self.violations()
        if v:
            lines = [
                f"  {x['fn']} {x['shapes']} at {x['callsite']}"
                for x in v
            ]
            raise SteadyStateViolation(
                f"{len(v)} compile(s) after the steady-state mark:\n"
                + "\n".join(lines)
            )

    def report(self) -> Dict[str, Any]:
        """Deterministically ordered snapshot (ms rounded to whole
        milliseconds so serializing the same state is byte-stable)."""
        with self._lock:
            fns = {
                name: {
                    "episodes": rec["episodes"],
                    "by_phase": dict(sorted(rec["by_phase"].items())),
                    "trace_ms": int(round(rec["trace_ms"])),
                    "lower_ms": int(round(rec["lower_ms"])),
                    "compile_ms": int(round(rec["compile_ms"])),
                }
                for name, rec in sorted(self._fns.items())
            }
            violations = list(self._violations)
            phase = self._phase
        totals = {p: 0 for p in (PHASE_STARTUP, PHASE_WARMUP,
                                 PHASE_STEADY)}
        for rec in fns.values():
            for p, n in rec["by_phase"].items():
                totals[p] = totals.get(p, 0) + n
        return {
            "phase": phase,
            "functions": fns,
            "total_compiles": sum(r["episodes"] for r in fns.values()),
            "by_phase": totals,
            "violations": violations,
        }

    def to_json(self) -> str:
        return json.dumps(self.report(), indent=2, sort_keys=True) + "\n"

    def reset(self) -> None:
        """Clear all state in place (the module-level jax.monitoring
        listener cannot be unregistered individually; the singleton it
        dispatches to resets instead)."""
        with self._lock:
            self._phase = PHASE_STARTUP
            self._warmup_depth = 0
            self._armed = False
            self._fns = {}
            self._violations = []


_ledger = CompileLedger()
_listener_lock = threading.Lock()
_listener_installed = False


def get_ledger() -> CompileLedger:
    """The process ledger, with the jax.monitoring listener installed on
    first use (import stays jax-free for stdlib-only consumers)."""
    global _listener_installed
    if not _listener_installed:
        with _listener_lock:
            if not _listener_installed:
                from jax import monitoring

                monitoring.register_event_duration_secs_listener(
                    _dispatch_event
                )
                _listener_installed = True
    return _ledger


def _dispatch_event(event: str, duration_secs: float, **_kw: Any) -> None:
    if event in (_EV_TRACE, _EV_LOWER, _EV_BACKEND):
        _ledger._on_event(event, duration_secs * 1000.0)


def instrument(name: str, fn: Callable[..., Any]) -> Callable[..., Any]:
    """Module-level convenience: wrap ``fn`` against the process
    ledger (see :meth:`CompileLedger.instrument`)."""
    return get_ledger().instrument(name, fn)
