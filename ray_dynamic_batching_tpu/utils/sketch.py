"""Mergeable relative-error quantile sketch (DDSketch-style).

The latency budget ledger needs percentiles that (a) hold a guaranteed
error bound so a budget ceiling means something, (b) merge across
processes/files/shards associatively so per-hop sketches from N capture
files aggregate into one fleet view, and (c) serialize byte-
deterministically so CI can diff them. A :class:`RollingWindow` gives
exact percentiles but only over its last N observations, does not merge,
and costs an O(n log n) sort per read on the hot path; a Prometheus
histogram merges but its percentile is a bucket upper bound whose error
is unbounded relative to the true value (see
``utils.metrics.Histogram.percentile``).

DDSketch (Masson et al., VLDB '19) fixes all three: logarithmic buckets
``[gamma^(i-1), gamma^i]`` with ``gamma = (1+alpha)/(1-alpha)`` guarantee
every reported quantile ``q`` satisfies ``|q - q_true| <= alpha *
q_true`` (relative, not absolute — exactly what latency percentiles
spanning microseconds to minutes need), buckets are integer counts so
merge is exact addition (associative, commutative, byte-deterministic),
and the whole state is a sparse int->int map that serializes to sorted
JSON.

Bounded memory: past ``max_bins`` distinct buckets the LOWEST buckets
collapse into one floor bucket (standard DDSketch policy — the high
quantiles the budget ledger gates on keep full accuracy; sub-floor
values degrade toward an upper-bound estimate). Collapse is the one
operation that can break strict merge associativity, so the default
``max_bins`` (2048) is sized to cover 1 us .. ~30 min of latency without
ever collapsing at the default accuracy; the collapse path is still
deterministic for a fixed observation order.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Iterable, Optional

from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock, assert_owner

__all__ = ["QuantileSketch", "RollingSketch"]


class QuantileSketch:
    """DDSketch with a contiguous-from-sparse bucket map.

    API is a strict superset of the deprecated ``RollingWindow``
    (``observe`` / ``percentile`` / ``mean`` / ``__len__``) so hot-path
    call sites swap without adaptation. NOT thread-safe — owners lock
    (the queue's lock already serializes its stats writes, and the
    metric family wraps access in the registry lock).
    """

    def __init__(self, relative_accuracy: float = 0.01,
                 max_bins: int = 2048, min_value: float = 1e-3) -> None:
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError(
                f"relative_accuracy must be in (0, 1), got {relative_accuracy}"
            )
        if max_bins < 2:
            raise ValueError(f"max_bins must be >= 2, got {max_bins}")
        if min_value <= 0.0:
            raise ValueError(f"min_value must be > 0, got {min_value}")
        self.relative_accuracy = float(relative_accuracy)
        self.max_bins = int(max_bins)
        self.min_value = float(min_value)
        self.gamma = (1.0 + relative_accuracy) / (1.0 - relative_accuracy)
        self._log_gamma = math.log(self.gamma)
        self._bins: Dict[int, int] = {}   # bucket index -> count
        self._zero = 0                    # observations in [0, min_value)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    # --- write side -------------------------------------------------------
    def _index(self, value: float) -> int:
        return int(math.ceil(math.log(value) / self._log_gamma))

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``value`` ``n`` times. Negative values are refused
        loudly: every consumer here measures durations, and a negative
        duration is an upstream bug the sketch must not launder into a
        plausible percentile."""
        if n <= 0:
            raise ValueError(f"n must be positive, got {n}")
        value = float(value)
        if value < 0.0 or math.isnan(value) or math.isinf(value):
            raise ValueError(f"cannot observe {value!r} (finite >= 0 only)")
        if value < self.min_value:
            self._zero += n
        else:
            i = self._index(value)
            self._bins[i] = self._bins.get(i, 0) + n
            if len(self._bins) > self.max_bins:
                self._collapse()
        self._count += n
        self._sum += value * n
        self._min = value if self._min is None else min(self._min, value)
        self._max = value if self._max is None else max(self._max, value)

    def _collapse(self) -> None:
        """Fold the lowest bins into one floor bin until ``max_bins``
        holds. Deterministic (sorted index order); preserves total count
        and keeps every bin ABOVE the floor exact, so high quantiles —
        the ones budgets gate — never lose accuracy."""
        indices = sorted(self._bins)
        # Fold exactly the excess: ending at max_bins bins, not
        # max_bins - 1 — each extra folded bin is low-quantile
        # resolution thrown away beyond what the bound requires.
        n_fold = len(indices) - self.max_bins
        floor_idx = indices[n_fold]  # survivors: indices[n_fold:]
        folded = sum(self._bins.pop(i) for i in indices[:n_fold])
        self._bins[floor_idx] += folded

    # --- read side --------------------------------------------------------
    @property
    def count(self) -> int:
        return self._count

    def __len__(self) -> int:
        return self._count

    def sum(self) -> float:
        return self._sum

    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def min(self) -> float:
        return self._min if self._min is not None else 0.0

    def max(self) -> float:
        return self._max if self._max is not None else 0.0

    def quantile(self, p: float) -> float:
        """Value at quantile ``p`` in [0, 1], within ``relative_accuracy``
        of the true rank value (nearest-rank, the live queue's rule).
        0.0 on an empty sketch — the callers' no-data convention."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {p}")
        if self._count == 0:
            return 0.0
        target = max(1, math.ceil(p * self._count))
        if target <= self._zero:
            return 0.0
        cum = self._zero
        for i in sorted(self._bins):
            cum += self._bins[i]
            if cum >= target:
                # Bucket i covers (gamma^(i-1), gamma^i]; the midpoint
                # estimate 2*gamma^i/(gamma+1) is within alpha of every
                # value in the bucket. Clamp to the observed extremes so
                # a single-value sketch reads back that value.
                est = 2.0 * (self.gamma ** i) / (self.gamma + 1.0)
                lo = self._min if self._min is not None else est
                hi = self._max if self._max is not None else est
                return min(max(est, lo), hi)
        return self._max if self._max is not None else 0.0

    def percentile(self, p: float) -> float:
        """RollingWindow-compatible alias of :meth:`quantile`."""
        return self.quantile(p)

    # --- merge + serialization -------------------------------------------
    def _compatible(self, other: "QuantileSketch") -> None:
        if (other.relative_accuracy != self.relative_accuracy
                or other.min_value != self.min_value):
            raise ValueError(
                "cannot merge sketches with different parameters "
                f"(alpha {self.relative_accuracy} vs "
                f"{other.relative_accuracy}, min_value {self.min_value} "
                f"vs {other.min_value}) — a silently re-bucketed merge "
                "would void the error bound"
            )

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into this sketch (exact integer bucket adds:
        associative and commutative as long as neither side collapses).
        Returns self for chaining."""
        self._compatible(other)
        for i, n in other._bins.items():
            self._bins[i] = self._bins.get(i, 0) + n
        if len(self._bins) > self.max_bins:
            self._collapse()
        self._zero += other._zero
        self._count += other._count
        self._sum += other._sum
        for v in (other._min,):
            if v is not None:
                self._min = v if self._min is None else min(self._min, v)
        for v in (other._max,):
            if v is not None:
                self._max = v if self._max is None else max(self._max, v)
        return self

    @classmethod
    def merged(cls, sketches: Iterable["QuantileSketch"],
               **kwargs: Any) -> "QuantileSketch":
        """A fresh sketch holding the union of ``sketches``."""
        out: Optional[QuantileSketch] = None
        for s in sketches:
            if out is None:
                out = cls(relative_accuracy=s.relative_accuracy,
                          max_bins=s.max_bins, min_value=s.min_value)
            out.merge(s)
        return out if out is not None else cls(**kwargs)

    def to_dict(self) -> Dict[str, Any]:
        """Canonical serialization: sorted integer bin keys (as strings —
        JSON object keys), so ``json.dumps(..., sort_keys=True)`` of two
        equal sketches is byte-identical."""
        return {
            "kind": "ddsketch",
            "relative_accuracy": self.relative_accuracy,
            "max_bins": self.max_bins,
            "min_value": self.min_value,
            "zero": self._zero,
            "count": self._count,
            "sum": self._sum,
            "min": self._min,
            "max": self._max,
            "bins": {str(i): self._bins[i] for i in sorted(self._bins)},
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "QuantileSketch":
        if d.get("kind") != "ddsketch":
            raise ValueError(f"not a ddsketch payload: kind={d.get('kind')!r}")
        out = cls(relative_accuracy=float(d["relative_accuracy"]),
                  max_bins=int(d["max_bins"]),
                  min_value=float(d["min_value"]))
        out._zero = int(d.get("zero", 0))
        out._count = int(d.get("count", 0))
        out._sum = float(d.get("sum", 0.0))
        out._min = None if d.get("min") is None else float(d["min"])
        out._max = None if d.get("max") is None else float(d["max"])
        out._bins = {int(k): int(v) for k, v in (d.get("bins") or {}).items()}
        return out

    def summary(self, quantiles: Iterable[float] = (0.5, 0.95, 0.99)
                ) -> Dict[str, float]:
        """Small stats block for reports: count + requested quantiles."""
        out: Dict[str, float] = {"count": float(self._count)}
        for q in quantiles:
            out[f"p{round(q * 100):d}_ms"] = self.quantile(q)
        return out

    def __repr__(self) -> str:
        return (f"QuantileSketch(alpha={self.relative_accuracy}, "
                f"count={self._count}, bins={len(self._bins)})")


class RollingSketch:
    """Recency-bounded quantile sketch: two :class:`QuantileSketch`
    epochs rotated every ``window`` observations, reads merged over
    both.

    Compliance signals (the queue's retry hints, failover's queue-health
    p50) need percentiles that track the LAST ~window completions — a
    cumulative sketch never forgets, so after hours of healthy traffic
    an overload's slow samples are a vanishing minority and the signal
    reports the healthy past long into the incident. Rotation bounds
    staleness: a read reflects at most the last ``2 * window``
    observations (current epoch + the sealed previous one), matching the
    deprecated ``RollingWindow(window)``'s recency contract while
    keeping the sketch's error bound (epoch merge is exact) and O(bins)
    reads instead of an O(n log n) sort under the owner's lock.

    Same read/write surface as :class:`QuantileSketch`, and — unlike
    the bare sketch — THREAD-SAFE, because its call sites are cross-
    thread by design: the engine thread observes completions while the
    failover worker and monitoring threads read percentiles with no
    shared lock (the contract ``RollingWindow`` held via its internal
    lock; without one, a concurrent observe mutates the bin dict under
    the sorted-bin walk of a reader's quantile and raises "dictionary
    changed size during iteration").
    """

    def __init__(self, window: int = 1000,
                 relative_accuracy: float = 0.01,
                 max_bins: int = 2048, min_value: float = 1e-3) -> None:
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        self.window = int(window)
        self._params = dict(relative_accuracy=relative_accuracy,
                            max_bins=max_bins, min_value=min_value)
        self._cur = QuantileSketch(**self._params)
        self._prev: Optional[QuantileSketch] = None
        self._total = 0
        self._lock = OrderedLock("sketch")

    @property
    def relative_accuracy(self) -> float:
        return self._cur.relative_accuracy  # rdb-lint: disable=lock-discipline (config read: every epoch's sketch is built from the same _params, so either epoch object answers identically)

    def observe(self, value: float, n: int = 1) -> None:
        with self._lock:
            if self._cur.count >= self.window:
                self._prev, self._cur = (
                    self._cur, QuantileSketch(**self._params)
                )
            self._cur.observe(value, n)
            self._total += n

    def _view(self) -> QuantileSketch:
        """Caller must hold ``self._lock``."""
        assert_owner(self._lock)
        if self._prev is None:
            return self._cur
        merged = QuantileSketch(**self._params)
        merged.merge(self._prev)
        merged.merge(self._cur)
        return merged

    def view(self) -> QuantileSketch:
        """A point-in-time COPY of the recency-bounded read view — safe
        to hold, read, or :meth:`QuantileSketch.merged` across instances
        (cross-queue aggregation) without this sketch's lock."""
        with self._lock:
            out = QuantileSketch(**self._params)
            out.merge(self._cur)
            if self._prev is not None:
                out.merge(self._prev)
            return out

    @property
    def count(self) -> int:
        """Observations in the current read view (recency-bounded);
        ``total`` counts everything ever observed."""
        with self._lock:
            return self._cur.count + (0 if self._prev is None
                                      else self._prev.count)

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def __len__(self) -> int:
        return self.count

    def mean(self) -> float:
        with self._lock:
            return self._view().mean()

    def min(self) -> float:
        with self._lock:
            return self._view().min()

    def max(self) -> float:
        with self._lock:
            return self._view().max()

    def quantile(self, p: float) -> float:
        with self._lock:
            return self._view().quantile(p)

    def percentile(self, p: float) -> float:
        return self.quantile(p)

    def summary(self, quantiles: Iterable[float] = (0.5, 0.95, 0.99)
                ) -> Dict[str, float]:
        with self._lock:
            return self._view().summary(quantiles)

    def __repr__(self) -> str:
        return (f"RollingSketch(window={self.window}, "
                f"count={self.count}, total={self._total})")  # rdb-lint: disable=lock-discipline (debug repr: a torn count is cosmetic, and taking the lock here could self-deadlock a log line emitted under it)
