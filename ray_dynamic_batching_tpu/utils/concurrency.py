"""Declared lock hierarchy + order-enforcing lock wrappers.

ONE model shared by the runtime and the linter, the ``ops/tile_math.py``
pattern applied to concurrency: :data:`LOCK_RANKS` names every lock
family in the stack and fixes the order they may nest (lower rank =
acquired first / outermost). The ``lock-ordering`` rule
(``tools/lint/lockorder.py``) loads this module STANDALONE (importlib,
no package import) and resolves ``OrderedLock("<rank>")`` construction
sites against the same table it enforces at runtime — the static model
and the armed runtime check cannot drift apart.

Runtime side:

- :class:`OrderedLock` wraps a ``threading.Lock`` (or ``RLock`` with
  ``reentrant=True``) and, when ``RDB_TESTING_LOCKORDER`` is armed,
  raises :class:`LockOrderError` the moment a thread acquires a rank
  less than or equal to one it already holds — a potential deadlock is
  reported on the FIRST inverted acquisition, deterministic, without
  needing the interleaving that would actually deadlock. Unarmed (the
  production default) the wrapper is one attribute check over the bare
  lock.
- :func:`assert_owner` asserts the calling thread holds a lock — and
  doubles as a lexical marker the ``lock-discipline`` rule understands:
  a method that opens with ``assert_owner(self._lock)`` declares its
  whole body runs under that lock (callers must hold it).

Deliberately dependency-free (stdlib only, no jax, no package imports):
the linter loads this file standalone so ``python -m tools.lint`` runs
in environments without the accelerator stack.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

LOCKORDER_ENV_VAR = "RDB_TESTING_LOCKORDER"

# The declared hierarchy: rank name -> level. A thread may only acquire
# STRICTLY INCREASING levels (outermost control plane first, leaf
# instrumentation last). Gaps of 10 leave room for new families without
# renumbering. Ownership rationale lives in ARCHITECTURE.md ("Lock
# hierarchy"); the short form:
#
#   controller     ServeController's control-step RLock — outermost: a
#                  control step calls into store, router, observatory.
#   store          ControllerStore/ReplicatedStore txn lock — commits
#                  fan out to lease probes and log appends.
#   lease          LeaderLease grant state — probed on the commit path.
#   store_log      StoreLog append/read — innermost durability lock.
#   router_pool    Router pow-2 pool + breakers — assignment enqueues
#                  into replica queues.
#   failover       FailoverManager retry heap/stats — its worker
#                  re-dispatches into queues (never holding the cond).
#   observatory    burn/forecast/fidelity monitors — ticks read queue
#                  windows and write gauges.
#   request_queue  RequestQueue buckets/counters/cond — completion
#                  paths touch token streams and metrics.
#   token_stream   Request future + TokenStream chunk cond — leaf of
#                  the request path (callbacks run outside it).
#   allocator      PageAllocator free-list — single-owner (engine step
#                  thread) today; the rank reserves its slot below the
#                  queue for the disagg/live-migration work.
#   fabric         ControlFabric chaos/stats — never held across a
#                  delivery; near-leaf by design.
#   sketch         RollingSketch epoch state — read under queue /
#                  observatory locks.
#   compile_ledger CompileLedger episode/violation state — updated from
#                  jax.monitoring callbacks during dispatch; bumps the
#                  rdb_jit_compiles_total counter while held, so it must
#                  sit ABOVE every dispatcher lock and BELOW metrics.
#   metrics        Metric/registry state — THE innermost: counters are
#                  bumped under every other lock in the stack.
LOCK_RANKS: Dict[str, int] = {
    "controller": 10,
    "store": 20,
    "lease": 30,
    "store_log": 40,
    "router_pool": 50,
    "failover": 60,
    "observatory": 70,
    "request_queue": 80,
    "token_stream": 90,
    "allocator": 100,
    "fabric": 110,
    "sketch": 120,
    "compile_ledger": 125,
    "metrics": 130,
}


def lockorder_armed() -> bool:
    """True when ``RDB_TESTING_LOCKORDER`` is set to a truthy value.
    Read at :class:`OrderedLock` construction (locks are built at
    component construction, which is when tests/soaks arm the env)."""
    return os.environ.get(LOCKORDER_ENV_VAR, "") not in ("", "0", "false")


class LockOrderError(RuntimeError):
    """A thread acquired lock ranks out of hierarchy order (potential
    deadlock), released a lock it does not own, or failed an
    :func:`assert_owner` check."""


_tls = threading.local()


def _held_stack() -> List[Tuple[int, str, int]]:
    """Per-thread stack of (level, rank_name, lock_id) held ARMED locks.
    The strict-increase invariant keeps it sorted; the top is the max."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def held_ranks() -> List[str]:
    """Rank names the calling thread currently holds (outermost first);
    empty when unarmed — only armed locks register themselves."""
    return [name for _, name, _ in _held_stack()]


class OrderedLock:
    """A ``threading.Lock``/``RLock`` that knows its place.

    ``rank`` must name an entry of :data:`LOCK_RANKS`. Context-manager
    and ``acquire``/``release``/``locked`` surfaces match the stdlib
    lock, and ``threading.Condition(OrderedLock(...))`` works (the
    wrapper provides ``_is_owned`` so the condition never try-acquires
    to probe ownership). When armed, acquisition order is checked
    BEFORE blocking, so an inversion is reported even on interleavings
    that would not have deadlocked this run.
    """

    def __init__(self, rank: str, *, reentrant: bool = False,
                 armed: Optional[bool] = None) -> None:
        if rank not in LOCK_RANKS:
            raise ValueError(
                f"unknown lock rank '{rank}' — declare it in "
                f"LOCK_RANKS (known: {', '.join(sorted(LOCK_RANKS))})"
            )
        self.rank_name = rank
        self.level = LOCK_RANKS[rank]
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._armed = lockorder_armed() if armed is None else armed
        self._owner: Optional[int] = None  # thread ident, armed only
        self._depth = 0

    # --- stdlib lock surface ----------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._armed:
            me = threading.get_ident()
            if not (self.reentrant and self._owner == me):
                stack = _held_stack()
                if stack and self.level <= stack[-1][0]:
                    top_level, top_name, _ = stack[-1]
                    raise LockOrderError(
                        f"lock-order violation: acquiring "
                        f"'{self.rank_name}' (rank {self.level}) while "
                        f"holding '{top_name}' (rank {top_level}) — "
                        f"ranks must strictly increase; held: "
                        f"{' -> '.join(held_ranks())}"
                    )
        got = self._inner.acquire(blocking, timeout)
        if got and self._armed:
            me = threading.get_ident()
            if self._owner == me:
                self._depth += 1
            else:
                self._owner = me
                self._depth = 1
                _held_stack().append((self.level, self.rank_name, id(self)))
        return got

    def release(self) -> None:
        if self._armed:
            me = threading.get_ident()
            if self._owner != me:
                raise LockOrderError(
                    f"'{self.rank_name}' released by a thread that does "
                    "not own it"
                )
            self._depth -= 1
            if self._depth == 0:
                self._owner = None
                stack = _held_stack()
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i][2] == id(self):
                        del stack[i]
                        break
        self._inner.release()

    def locked(self) -> bool:
        if self.reentrant:
            # RLock has no .locked(); armed tracking answers instead.
            return self._owner is not None if self._armed \
                else self._inner._is_owned()  # type: ignore[attr-defined]
        return self._inner.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # --- ownership (Condition compat + assert_owner) ----------------------
    def held_by_me(self) -> Optional[bool]:
        """True/False when armed (ownership is tracked); None unarmed —
        a bare ``threading.Lock`` cannot name its owner."""
        if not self._armed:
            return None
        return self._owner == threading.get_ident()

    def _is_owned(self) -> bool:
        """``threading.Condition`` probes this instead of try-acquiring
        (a try-acquire under arming would trip the order check against
        the very lock the condition wraps)."""
        if self._armed:
            return self._owner == threading.get_ident()
        if self.reentrant:
            return self._inner._is_owned()  # type: ignore[attr-defined]
        # Stdlib fallback for a plain lock: owned iff not acquirable.
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def assert_owner(lock) -> None:
    """Assert the calling thread holds ``lock``.

    Doubles as the ``lock-discipline`` rule's guarded-context marker: a
    method whose body calls ``assert_owner(self._lock)`` is analyzed as
    running entirely under that lock — the callers are the ones that
    must hold it. At runtime the check is real only for an ARMED
    :class:`OrderedLock` (a bare ``threading.Lock`` cannot name its
    owner); unarmed or untracked locks pass silently, keeping the
    marker free on production paths.
    """
    held = getattr(lock, "held_by_me", None)
    if held is None:
        return
    owned = held()
    if owned is False:
        raise LockOrderError(
            f"assert_owner: calling thread does not hold "
            f"'{getattr(lock, 'rank_name', '?')}' (held: "
            f"{' -> '.join(held_ranks()) or 'nothing'})"
        )
