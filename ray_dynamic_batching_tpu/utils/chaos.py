"""Fault-injection hooks — the ``RAY_testing_rpc_failure`` equivalent.

The reference injects request/response failures at the RPC layer from an
env spec (``src/ray/rpc/rpc_chaos.h:23-31``, parsed at ``rpc_chaos.cc:32``:
``RAY_testing_rpc_failure=method1=N,method2=M``). Here the injection points
are the framework's own boundaries (replica batch execution, replica loop,
router assignment, ingress handling), named and budgeted the same way:

    RDB_TESTING_FAILURE="replica.process_batch=3,replica.loop=1"

Each ``point=N`` allows at most N injected failures (-1 = unlimited); an
optional ``:p<float>`` suffix makes injection probabilistic
(``point=5:p0.5`` — up to 5 failures, each opportunity failing with
probability 0.5). Injection is a no-op unless configured, so production
paths pay one dict lookup.

**Gray-failure (slowdown) modes** (ISSUE 9): binary death misses the
failures that actually erode SLO attainment — a replica running 5-10x
slow, a stall before the first token, a stream that never EOSes. A
second spec injects those, same grammar plus a mode suffix:

    RDB_TESTING_SLOWDOWN="replica.process_batch=-1:mult10"
    RDB_TESTING_SLOWDOWN="replica.process_batch=3:stall50:p0.5"
    RDB_TESTING_SLOWDOWN="replica.process_batch@soak#0=-1:mult10"

Modes: ``mult<F>`` (latency_multiplier — the batch takes F x as long),
``stall<MS>`` (stall_before_first_token — MS ms dead air before
execution), ``stuck<MS>`` (stuck_stream — output produced, EOS withheld
for MS ms). A ``point@instance`` key targets ONE replica/engine (the
straggler soak slows one replica of three); instance-less keys hit every
caller of the point. Probabilistic draws use the same seeded RNG
discipline as failures (``config.chaos_seed``), so a slowdown schedule
replays byte-identically.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

ENV_VAR = "RDB_TESTING_FAILURE"
SLOWDOWN_ENV_VAR = "RDB_TESTING_SLOWDOWN"
# Query-of-death injection (ISSUE 19): a batch whose payloads carry the
# poison marker raises at execution, driving the replica's bisection +
# quarantine path end-to-end. Grammar: ``point=N[:pP]`` — the first N
# DISTINCT marked requests seen at the point are armed as poisonous;
# an armed marker keeps failing every re-execution that contains it
# (bisection probes included), which is what makes isolation possible.
POISON_ENV_VAR = "RDB_TESTING_POISON"
# Payload marker: a dict payload with this key truthy (or a string
# payload containing the token) is poison-eligible. The VALUE of the
# marker is the poison's identity — distinct values are distinct
# poisons against the injection budget.
POISON_MARKER = "__rdb_poison__"

SLOWDOWN_MODES = (
    "latency_multiplier", "stall_before_first_token", "stuck_stream",
)


@dataclass(frozen=True)
class Slowdown:
    """One degradation verdict: HOW to be slow (the degradation
    taxonomy shared with ``sim.simulator.EngineDegradation``)."""

    mode: str                  # one of SLOWDOWN_MODES
    factor: float = 1.0        # latency_multiplier: execution time x F
    ms: float = 0.0            # stall/stuck: milliseconds of dead air


def _parse_slowdown_mode(token: str) -> Slowdown:
    if token.startswith("mult"):
        factor = float(token[4:])
        if factor < 1.0:
            raise ValueError(f"mult factor must be >= 1, got {factor}")
        return Slowdown("latency_multiplier", factor=factor)
    if token.startswith("stall"):
        return Slowdown("stall_before_first_token", ms=float(token[5:]))
    if token.startswith("stuck"):
        return Slowdown("stuck_stream", ms=float(token[5:]))
    raise ValueError(
        f"bad slowdown mode {token!r} (want mult<F>|stall<MS>|stuck<MS>)"
    )


class ChaosInjected(RuntimeError):
    """Raised at an injection point whose failure budget fired."""


class PoisonInjected(RuntimeError):
    """Raised by a batch execution containing an armed poison marker.

    Deliberately NOT a :class:`ChaosInjected` subclass: chaos failures
    classify *retryable* (the payload was never the problem) while a
    poison is the payload itself — it must reach the replica's
    non-retryable path so bisection, not failover, handles it."""


class ChaosInjector:
    def __init__(self, spec: Optional[str] = None,
                 seed: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._budgets: Dict[str, int] = {}
        self._probs: Dict[str, float] = {}
        self._fired: Dict[str, int] = {}
        # Probabilistic injections draw from a dedicated seeded RNG so a
        # chaos run replays deterministically; the seed comes from
        # ``config.chaos_seed`` (RDB_CHAOS_SEED) unless given explicitly.
        self._seed = seed if seed is not None else self._config_seed()
        self._rng = random.Random(self._seed)
        self._active = False  # unlocked fast-path flag for hot callers
        # Slowdown (gray-failure) injection state: its own budgets, fired
        # counts, seeded RNG and fast-path flag — a failure budget and a
        # slowdown budget on the same point are independent.
        self._slow: Dict[str, Tuple[int, float, Slowdown]] = {}
        self._slow_fired: Dict[str, int] = {}
        self._slow_rng = random.Random(self._seed)
        self._slow_active = False
        # Poison (query-of-death) injection state: budgets count DISTINCT
        # armed markers; an armed marker stays poisonous for every later
        # execution containing it (bisection needs the fault to follow
        # the request through probe subsets deterministically).
        self._poison_budgets: Dict[str, int] = {}
        self._poison_probs: Dict[str, float] = {}
        self._poison_armed: Dict[str, set] = {}
        self._poison_fired: Dict[str, int] = {}
        self._poison_rng = random.Random(self._seed)
        self._poison_active = False
        self.configure(spec if spec is not None else os.environ.get(ENV_VAR, ""))
        self.configure_slowdowns(os.environ.get(SLOWDOWN_ENV_VAR, ""))
        self.configure_poisons(os.environ.get(POISON_ENV_VAR, ""))

    @staticmethod
    def _config_seed() -> int:
        from ray_dynamic_batching_tpu.utils.config import get_config

        return get_config().chaos_seed

    def configure(self, spec: str, seed: Optional[int] = None) -> None:
        """Parse ``point=N[:pP],point2=M`` (reference rpc_chaos.cc:32).
        Parses fully before swapping state, so an invalid spec leaves the
        previous configuration untouched. Every (re)configure reseeds the
        injection RNG — same spec + same seed replays the same failure
        schedule (``seed`` overrides the configured default)."""
        budgets: Dict[str, int] = {}
        probs: Dict[str, float] = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            if "=" not in part:
                raise ValueError(f"bad chaos spec entry {part!r}")
            point, rhs = part.split("=", 1)
            prob = 1.0
            if ":p" in rhs:
                rhs, prob_s = rhs.split(":p", 1)
                prob = float(prob_s)
            budgets[point.strip()] = int(rhs)
            probs[point.strip()] = prob
        with self._lock:
            self._budgets = budgets
            self._probs = probs
            self._fired = {}
            if seed is not None:
                self._seed = seed
            self._rng = random.Random(self._seed)
            self._active = bool(budgets)

    def should_fail(self, point: str) -> bool:
        """Consume one unit of the point's failure budget (thread-safe).
        Free when chaos is unconfigured: one unlocked attribute read."""
        if not self._active:  # rdb-lint: disable=lock-discipline (unconfigured fast path: arming flips in quiesced configure(); one-op staleness only shifts chaos onset by one call)
            return False
        with self._lock:
            budget = self._budgets.get(point)
            if budget is None or budget == 0:
                return False
            if self._probs.get(point, 1.0) < 1.0:
                if self._rng.random() >= self._probs[point]:
                    return False
            if budget > 0:
                self._budgets[point] = budget - 1
            self._fired[point] = self._fired.get(point, 0) + 1
            return True

    def maybe_fail(self, point: str) -> None:
        if self.should_fail(point):
            raise ChaosInjected(f"chaos injected at {point}")

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)

    # --- slowdown (gray-failure) injection --------------------------------
    def configure_slowdowns(self, spec: str,
                            seed: Optional[int] = None) -> None:
        """Parse ``point[@instance]=N:mode[:pP],...``. Same all-or-
        nothing swap and reseed-on-configure discipline as
        :meth:`configure`: same spec + same seed replays the same
        slowdown schedule byte-identically (the seeded-replay pin)."""
        table: Dict[str, Tuple[int, float, Slowdown]] = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            if "=" not in part:
                raise ValueError(f"bad slowdown spec entry {part!r}")
            point, rhs = part.split("=", 1)
            prob = 1.0
            tokens = rhs.split(":")
            if len(tokens) < 2:
                raise ValueError(
                    f"slowdown entry {part!r} needs a mode "
                    "(point=N:mult<F>|stall<MS>|stuck<MS>[:pP])"
                )
            if len(tokens) > 2:
                if not tokens[2].startswith("p"):
                    raise ValueError(
                        f"bad slowdown suffix {tokens[2]!r} (want p<float>)"
                    )
                prob = float(tokens[2][1:])
            table[point.strip()] = (
                int(tokens[0]), prob, _parse_slowdown_mode(tokens[1])
            )
        with self._lock:
            self._slow = table
            self._slow_fired = {}
            if seed is not None:
                self._seed = seed
            self._slow_rng = random.Random(self._seed)
            self._slow_active = bool(table)

    def slowdown(self, point: str,
                 instance: Optional[str] = None) -> Optional[Slowdown]:
        """The degradation to apply at this point right now, or None.
        ``point@instance`` entries outrank bare ``point`` entries so a
        spec can slow exactly one replica of a fleet. Consumes one unit
        of the matched entry's budget. Free when unconfigured: one
        unlocked attribute read."""
        if not self._slow_active:  # rdb-lint: disable=lock-discipline (unconfigured fast path: arming flips in quiesced configure(); one-op staleness only shifts chaos onset by one call)
            return None
        keys = ([f"{point}@{instance}"] if instance is not None else [])
        keys.append(point)
        with self._lock:
            for key in keys:
                entry = self._slow.get(key)
                if entry is None:
                    continue
                budget, prob, verdict = entry
                if budget == 0:
                    continue
                if prob < 1.0 and self._slow_rng.random() >= prob:
                    return None  # this opportunity drew a pass
                if budget > 0:
                    self._slow[key] = (budget - 1, prob, verdict)
                self._slow_fired[key] = self._slow_fired.get(key, 0) + 1
                return verdict
            return None

    def slowdown_fired(self, point: str,
                       instance: Optional[str] = None) -> int:
        key = f"{point}@{instance}" if instance is not None else point
        with self._lock:
            return self._slow_fired.get(key, 0)

    # --- poison (query-of-death) injection --------------------------------
    def configure_poisons(self, spec: str,
                          seed: Optional[int] = None) -> None:
        """Parse ``point=N[:pP],...`` — same grammar and all-or-nothing
        swap/reseed discipline as :meth:`configure`. ``N`` bounds how
        many DISTINCT poison markers may arm at the point (-1 =
        unlimited); ``:pP`` makes each arming opportunity probabilistic
        over the seeded RNG."""
        budgets: Dict[str, int] = {}
        probs: Dict[str, float] = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            if "=" not in part:
                raise ValueError(f"bad poison spec entry {part!r}")
            point, rhs = part.split("=", 1)
            prob = 1.0
            if ":p" in rhs:
                rhs, prob_s = rhs.split(":p", 1)
                prob = float(prob_s)
            budgets[point.strip()] = int(rhs)
            probs[point.strip()] = prob
        with self._lock:
            self._poison_budgets = budgets
            self._poison_probs = probs
            self._poison_armed = {}
            self._poison_fired = {}
            if seed is not None:
                self._seed = seed
            self._poison_rng = random.Random(self._seed)
            self._poison_active = bool(budgets)

    @staticmethod
    def poison_marker(payload) -> Optional[str]:
        """The payload's poison identity, or None. Dict payloads carry
        ``{POISON_MARKER: <id>}``; string payloads embed the token."""
        if isinstance(payload, dict):
            marker = payload.get(POISON_MARKER)
            if marker:
                return str(marker)
            return None
        if isinstance(payload, str) and POISON_MARKER in payload:
            return payload
        return None

    def poison_verdict(self, point: str, payloads) -> Optional[int]:
        """Index of the first poisonous payload in this execution, or
        None. An already-armed marker fires WITHOUT consuming budget (a
        poison stays poisonous — that is what bisection relies on); an
        unarmed marker arms iff the point's distinct-marker budget and
        probability allow. Free when unconfigured: one unlocked read."""
        if not self._poison_active:  # rdb-lint: disable=lock-discipline (unconfigured fast path: arming flips in quiesced configure_poisons(); one-op staleness only shifts poison onset by one call)
            return None
        with self._lock:
            budget = self._poison_budgets.get(point)
            if budget is None:
                return None
            armed = self._poison_armed.setdefault(point, set())
            for idx, payload in enumerate(payloads):
                marker = self.poison_marker(payload)
                if marker is None:
                    continue
                if marker in armed:
                    self._poison_fired[point] = \
                        self._poison_fired.get(point, 0) + 1
                    return idx
                if budget == 0:
                    continue
                prob = self._poison_probs.get(point, 1.0)
                if prob < 1.0 and self._poison_rng.random() >= prob:
                    continue
                armed.add(marker)
                if budget > 0:
                    self._poison_budgets[point] = budget - 1
                    budget -= 1
                self._poison_fired[point] = \
                    self._poison_fired.get(point, 0) + 1
                return idx
            return None

    def maybe_poison(self, point: str, payloads) -> None:
        idx = self.poison_verdict(point, payloads)
        if idx is not None:
            raise PoisonInjected(
                f"poison injected at {point} (batch index {idx})"
            )

    def poison_fired(self, point: str) -> int:
        with self._lock:
            return self._poison_fired.get(point, 0)

    @property
    def active(self) -> bool:
        return self._active  # rdb-lint: disable=lock-discipline (observability read of the arming flag; torn/stale by one op is benign)


_GLOBAL: Optional[ChaosInjector] = None
_GLOBAL_LOCK = threading.Lock()


def chaos() -> ChaosInjector:
    """Process-global injector, configured from the environment on first
    use (mirrors the reference's static init)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = ChaosInjector()
    return _GLOBAL


def reset_chaos(spec: str = "", seed: Optional[int] = None,
                slowdown: str = "", poison: str = "") -> ChaosInjector:
    """Re-configure (and optionally reseed) the global injector (tests /
    soak harnesses): ``reset_chaos(spec, seed=N)`` pins the probabilistic
    failure schedule for a deterministic replay. ``slowdown`` carries the
    gray-failure spec and ``poison`` the query-of-death spec — both
    cleared by default, so every existing ``reset_chaos("")`` teardown
    also disarms them."""
    inj = chaos()
    inj.configure(spec, seed=seed)
    inj.configure_slowdowns(slowdown, seed=seed)
    inj.configure_poisons(poison, seed=seed)
    return inj
