"""Fault-injection hooks — the ``RAY_testing_rpc_failure`` equivalent.

The reference injects request/response failures at the RPC layer from an
env spec (``src/ray/rpc/rpc_chaos.h:23-31``, parsed at ``rpc_chaos.cc:32``:
``RAY_testing_rpc_failure=method1=N,method2=M``). Here the injection points
are the framework's own boundaries (replica batch execution, replica loop,
router assignment, ingress handling), named and budgeted the same way:

    RDB_TESTING_FAILURE="replica.process_batch=3,replica.loop=1"

Each ``point=N`` allows at most N injected failures (-1 = unlimited); an
optional ``:p<float>`` suffix makes injection probabilistic
(``point=5:p0.5`` — up to 5 failures, each opportunity failing with
probability 0.5). Injection is a no-op unless configured, so production
paths pay one dict lookup.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, Optional, Tuple

ENV_VAR = "RDB_TESTING_FAILURE"


class ChaosInjected(RuntimeError):
    """Raised at an injection point whose failure budget fired."""


class ChaosInjector:
    def __init__(self, spec: Optional[str] = None,
                 seed: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._budgets: Dict[str, int] = {}
        self._probs: Dict[str, float] = {}
        self._fired: Dict[str, int] = {}
        # Probabilistic injections draw from a dedicated seeded RNG so a
        # chaos run replays deterministically; the seed comes from
        # ``config.chaos_seed`` (RDB_CHAOS_SEED) unless given explicitly.
        self._seed = seed if seed is not None else self._config_seed()
        self._rng = random.Random(self._seed)
        self._active = False  # unlocked fast-path flag for hot callers
        self.configure(spec if spec is not None else os.environ.get(ENV_VAR, ""))

    @staticmethod
    def _config_seed() -> int:
        from ray_dynamic_batching_tpu.utils.config import get_config

        return get_config().chaos_seed

    def configure(self, spec: str, seed: Optional[int] = None) -> None:
        """Parse ``point=N[:pP],point2=M`` (reference rpc_chaos.cc:32).
        Parses fully before swapping state, so an invalid spec leaves the
        previous configuration untouched. Every (re)configure reseeds the
        injection RNG — same spec + same seed replays the same failure
        schedule (``seed`` overrides the configured default)."""
        budgets: Dict[str, int] = {}
        probs: Dict[str, float] = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            if "=" not in part:
                raise ValueError(f"bad chaos spec entry {part!r}")
            point, rhs = part.split("=", 1)
            prob = 1.0
            if ":p" in rhs:
                rhs, prob_s = rhs.split(":p", 1)
                prob = float(prob_s)
            budgets[point.strip()] = int(rhs)
            probs[point.strip()] = prob
        with self._lock:
            self._budgets = budgets
            self._probs = probs
            self._fired = {}
            if seed is not None:
                self._seed = seed
            self._rng = random.Random(self._seed)
            self._active = bool(budgets)

    def should_fail(self, point: str) -> bool:
        """Consume one unit of the point's failure budget (thread-safe).
        Free when chaos is unconfigured: one unlocked attribute read."""
        if not self._active:
            return False
        with self._lock:
            budget = self._budgets.get(point)
            if budget is None or budget == 0:
                return False
            if self._probs.get(point, 1.0) < 1.0:
                if self._rng.random() >= self._probs[point]:
                    return False
            if budget > 0:
                self._budgets[point] = budget - 1
            self._fired[point] = self._fired.get(point, 0) + 1
            return True

    def maybe_fail(self, point: str) -> None:
        if self.should_fail(point):
            raise ChaosInjected(f"chaos injected at {point}")

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)

    @property
    def active(self) -> bool:
        return self._active


_GLOBAL: Optional[ChaosInjector] = None
_GLOBAL_LOCK = threading.Lock()


def chaos() -> ChaosInjector:
    """Process-global injector, configured from the environment on first
    use (mirrors the reference's static init)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = ChaosInjector()
    return _GLOBAL


def reset_chaos(spec: str = "", seed: Optional[int] = None) -> ChaosInjector:
    """Re-configure (and optionally reseed) the global injector (tests /
    soak harnesses): ``reset_chaos(spec, seed=N)`` pins the probabilistic
    failure schedule for a deterministic replay."""
    inj = chaos()
    inj.configure(spec, seed=seed)
    return inj
