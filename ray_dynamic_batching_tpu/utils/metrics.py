"""Metrics primitives: Counter / Gauge / Histogram with Prometheus exposition.

TPU-native analogue of the reference's metric stack
(``python/ray/util/metrics.py:137,187,262`` user API;
``src/ray/stats/metric_defs.cc`` native registry;
``python/ray/_private/metrics_agent.py:483`` Prometheus export). Pure Python,
lock-protected, with a text exposition endpoint consumed by ``serve.ingress``.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
import warnings
from collections import deque
from typing import Dict, Iterable, Optional, Sequence, Tuple

from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch

TagMap = Tuple[Tuple[str, str], ...]

# Overflow bucket for bounded tag keys: once a key has minted its cap of
# distinct values, every further value collapses here — client-controlled
# identifiers (tenants) must not mint unbounded series cardinality.
OTHER_LABEL = "__other__"

# Default top-K for tenant labels (override per metric via bounded_tags).
DEFAULT_TENANT_TOP_K = 16

# Default top-K for front-door shard labels on proxy/router families: the
# shard id is infrastructure-controlled (not client input) but scales with
# the front-door fleet, so it is bounded the same way — a misconfigured
# 200-shard ring must not mint 200x series cardinality per family.
DEFAULT_SHARD_TOP_K = 8


def _tags(tags: Optional[Dict[str, str]]) -> TagMap:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = (),
                 bounded_tags: Optional[Dict[str, int]] = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        # tag key -> max distinct values; first-come keeps its own series,
        # overflow collapses to OTHER_LABEL (top-K by arrival order — the
        # stable tenants of a deployment register early and stay named).
        self.bounded_tags = dict(bounded_tags or {})
        self._bounded_seen: Dict[str, set] = {}
        self._lock = OrderedLock("metrics")
        _default_registry.register(self)

    def _normalize_tags(
        self, tags: Optional[Dict[str, str]], claim: bool = True
    ) -> Optional[Dict[str, str]]:
        """Collapse over-cap values of bounded tag keys to OTHER_LABEL.
        Applied on every write AND read so an overflowed value always
        addresses the same (overflow) series. Only WRITES claim a named
        top-K slot (``claim=True``); a read for a never-written value
        must not consume a slot a real series could still take."""
        if not self.bounded_tags or not tags:
            return tags
        out = None
        for key, cap in self.bounded_tags.items():
            value = (out or tags).get(key)
            if value is None or value == OTHER_LABEL:
                continue
            with self._lock:
                seen = self._bounded_seen.setdefault(key, set())
                if value in seen:
                    continue
                if len(seen) < cap:
                    if claim:
                        seen.add(value)
                    continue
            if out is None:
                out = dict(tags)
            out[key] = OTHER_LABEL
        return out if out is not None else tags

    def _check_tags(self, tags: Optional[Dict[str, str]]) -> None:
        # Declared tag_keys are enforced both ways (ref: ray.util.metrics API):
        # a typo'd OR omitted key fails loudly instead of minting a silent
        # parallel series.
        if self.tag_keys:
            given = set(tags or {})
            unknown = given - set(self.tag_keys)
            missing = set(self.tag_keys) - given
            if unknown or missing:
                raise ValueError(
                    f"metric {self.name!r}: tag keys mismatch "
                    f"(unknown={sorted(unknown)}, missing={sorted(missing)}); "
                    f"declared: {sorted(self.tag_keys)}"
                )

    def _prom_lines(self, exemplars: bool = False) -> Iterable[str]:
        # pragma: no cover - overridden
        return ()


class Counter(Metric):
    """Monotonically increasing counter (ref: util/metrics.py:137)."""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = (),
                 bounded_tags: Optional[Dict[str, int]] = None):
        super().__init__(name, description, tag_keys, bounded_tags)
        self._values: Dict[TagMap, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc requires value >= 0")
        self._check_tags(tags)
        key = _tags(self._normalize_tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def get(self, tags: Optional[Dict[str, str]] = None) -> float:
        key = _tags(self._normalize_tags(tags, claim=False))
        with self._lock:
            return self._values.get(key, 0.0)

    def _prom_lines(self, exemplars: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            for tags, v in self._values.items():
                yield f"{self.name}{_fmt_tags(tags)} {v}"


class Gauge(Metric):
    """Point-in-time value (ref: util/metrics.py:262)."""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = (),
                 bounded_tags: Optional[Dict[str, int]] = None):
        super().__init__(name, description, tag_keys, bounded_tags)
        self._values: Dict[TagMap, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        self._check_tags(tags)
        key = _tags(self._normalize_tags(tags))
        with self._lock:
            self._values[key] = float(value)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        self._check_tags(tags)
        key = _tags(self._normalize_tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        self.inc(-value, tags)

    def get(self, tags: Optional[Dict[str, str]] = None) -> float:
        key = _tags(self._normalize_tags(tags, claim=False))
        with self._lock:
            return self._values.get(key, 0.0)

    def _prom_lines(self, exemplars: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            for tags, v in self._values.items():
                yield f"{self.name}{_fmt_tags(tags)} {v}"


DEFAULT_LATENCY_BOUNDARIES_MS = (
    1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000
)


def _current_trace_id() -> Optional[str]:
    """Trace id of the active span, if the tracer is recording (exemplar
    auto-capture). Local import: metrics must stay importable before/without
    the tracing module in degraded environments."""
    try:
        from ray_dynamic_batching_tpu.utils.tracing import tracer
    except ImportError:  # pragma: no cover - only in stripped builds
        return None
    t = tracer()
    return t.current_trace_id() if t.enabled else None


class Histogram(Metric):
    """Cumulative-bucket histogram (ref: util/metrics.py:187).

    Buckets carry OpenMetrics **exemplars**: the last observation landing in
    each bucket remembers the trace_id that produced it (from the active
    span, or passed explicitly), so a slow ``/metrics`` bucket links
    straight to the flight-record trace that landed in it.
    """

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = DEFAULT_LATENCY_BOUNDARIES_MS,
        tag_keys: Sequence[str] = (),
        bounded_tags: Optional[Dict[str, int]] = None,
        track_quantiles: bool = False,
        relative_accuracy: float = 0.01,
    ):
        super().__init__(name, description, tag_keys, bounded_tags)
        self.boundaries = tuple(sorted(boundaries))
        self._buckets: Dict[TagMap, list] = {}
        self._sum: Dict[TagMap, float] = {}
        self._count: Dict[TagMap, int] = {}
        # Per (tags, bucket): (value, trace_id, unix_ts) of the most recent
        # traced observation in that bucket.
        self._exemplars: Dict[TagMap, list] = {}
        # Optional per-series quantile sketch: where a histogram already
        # carries exemplars (a latency family an operator reads
        # percentiles from), ``track_quantiles=True`` makes
        # :meth:`percentile` error-bounded instead of bucket-biased.
        # The exposition is unchanged — buckets and exemplars still
        # render; the sketch only backs in-process reads.
        self._sketch_accuracy = relative_accuracy if track_quantiles else None
        self._sketches: Dict[TagMap, QuantileSketch] = {}

    def observe(
        self,
        value: float,
        tags: Optional[Dict[str, str]] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        self._check_tags(tags)
        key = _tags(self._normalize_tags(tags))
        idx = bisect.bisect_left(self.boundaries, value)
        if trace_id is None:
            trace_id = _current_trace_id()
        with self._lock:
            buckets = self._buckets.setdefault(key, [0] * (len(self.boundaries) + 1))
            buckets[idx] += 1
            self._sum[key] = self._sum.get(key, 0.0) + value
            self._count[key] = self._count.get(key, 0) + 1
            if self._sketch_accuracy is not None and value >= 0.0:
                sk = self._sketches.get(key)
                if sk is None:
                    sk = self._sketches[key] = QuantileSketch(
                        relative_accuracy=self._sketch_accuracy
                    )
                sk.observe(value)
            if trace_id:
                ex = self._exemplars.setdefault(
                    key, [None] * (len(self.boundaries) + 1)
                )
                ex[idx] = (value, trace_id, time.time())

    def percentile(self, p: float, tags: Optional[Dict[str, str]] = None) -> float:
        """Approximate percentile.

        KNOWN BIAS (bucket path): the default implementation returns the
        UPPER BOUND of the cumulative bucket the rank lands in — e.g.
        with the default boundaries an observation set of all 21 ms
        reads back p50 = 50 ms, a 2.4x overstatement, and anything past
        the last boundary reads ``inf``. The error is unbounded relative
        to the true value (it depends entirely on where the boundaries
        fall), so alerting math on this path compares apples to bucket
        edges. Construct the histogram with ``track_quantiles=True`` to
        back this read with a relative-error quantile sketch
        (``utils.sketch.QuantileSketch``): the bias drops to the
        configured ``relative_accuracy`` while the exposition stays a
        plain histogram.
        """
        key = _tags(self._normalize_tags(tags, claim=False))
        with self._lock:
            sk = self._sketches.get(key)
            if sk is not None:
                return sk.quantile(p)
            buckets = self._buckets.get(key)
            total = self._count.get(key, 0)
        if not buckets or total == 0:
            return 0.0
        target = math.ceil(total * p)
        cum = 0
        for i, c in enumerate(buckets):
            cum += c
            if cum >= target:
                return self.boundaries[i] if i < len(self.boundaries) else float("inf")
        return float("inf")

    @staticmethod
    def _exemplar_suffix(ex) -> str:
        """OpenMetrics exemplar: `` # {trace_id="..."} value timestamp``."""
        if ex is None:
            return ""
        value, trace_id, ts = ex
        return f' # {{trace_id="{_escape_label(trace_id)}"}} {value} {ts:.3f}'

    def _prom_lines(self, exemplars: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            for key, buckets in self._buckets.items():
                # Exemplar suffixes are OpenMetrics syntax — emitted only
                # for OpenMetrics renders; the classic 0.0.4 text format
                # (a stock Prometheus scraper) must stay suffix-free or
                # the whole scrape fails to parse.
                ex = self._exemplars.get(key) if exemplars else None
                cum = 0
                for i, (b, c) in enumerate(zip(self.boundaries, buckets)):
                    cum += c
                    t = key + (("le", str(b)),)
                    yield (f"{self.name}_bucket{_fmt_tags(t)} {cum}"
                           + self._exemplar_suffix(ex[i] if ex else None))
                cum += buckets[-1]
                t = key + (("le", "+Inf"),)
                yield (f"{self.name}_bucket{_fmt_tags(t)} {cum}"
                       + self._exemplar_suffix(ex[-1] if ex else None))
                yield f"{self.name}_sum{_fmt_tags(key)} {self._sum.get(key, 0.0)}"
                yield f"{self.name}_count{_fmt_tags(key)} {self._count.get(key, 0)}"


class Sketch(Metric):
    """First-class mergeable quantile-sketch family (DDSketch-backed).

    One :class:`~ray_dynamic_batching_tpu.utils.sketch.QuantileSketch`
    per tag set. Exposed in the OpenMetrics/Prometheus ``summary``
    grammar — ``name{quantile="0.5"} v`` lines plus ``_sum``/``_count``
    — the one exposition type built for pre-computed quantiles. Unlike a
    native Prometheus summary the underlying state MERGES (sketch bucket
    adds are exact), so per-process series aggregate without the
    classic "can't average percentiles" trap; ``sketch_state`` hands the
    raw sketch out for cross-process merges and serialization.
    """

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = (),
                 quantiles: Sequence[float] = (0.5, 0.9, 0.95, 0.99),
                 relative_accuracy: float = 0.01,
                 bounded_tags: Optional[Dict[str, int]] = None):
        super().__init__(name, description, tag_keys, bounded_tags)
        if not quantiles or any(not 0.0 <= q <= 1.0 for q in quantiles):
            raise ValueError(f"quantiles must be in [0, 1]: {quantiles}")
        self.quantiles = tuple(sorted(quantiles))
        self.relative_accuracy = float(relative_accuracy)
        self._sketches: Dict[TagMap, QuantileSketch] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        self._check_tags(tags)
        key = _tags(self._normalize_tags(tags))
        with self._lock:
            sk = self._sketches.get(key)
            if sk is None:
                sk = self._sketches[key] = QuantileSketch(
                    relative_accuracy=self.relative_accuracy
                )
            sk.observe(value)

    def quantile(self, p: float,
                 tags: Optional[Dict[str, str]] = None) -> float:
        key = _tags(self._normalize_tags(tags, claim=False))
        # Reads stay INSIDE the lock: the bare sketch is unlocked, and a
        # concurrent observe mutating the bin dict under a reader's
        # sorted-bin walk raises "dictionary changed size".
        with self._lock:
            sk = self._sketches.get(key)
            return sk.quantile(p) if sk is not None else 0.0

    def percentile(self, p: float,
                   tags: Optional[Dict[str, str]] = None) -> float:
        return self.quantile(p, tags)

    def count(self, tags: Optional[Dict[str, str]] = None) -> int:
        key = _tags(self._normalize_tags(tags, claim=False))
        with self._lock:
            sk = self._sketches.get(key)
            return sk.count if sk is not None else 0

    def sketch_state(self, tags: Optional[Dict[str, str]] = None
                     ) -> Optional[Dict]:
        """Serialized sketch for this tag set (mergeable across
        processes via ``QuantileSketch.from_dict(...).merge(...)``);
        None when the series was never observed."""
        key = _tags(self._normalize_tags(tags, claim=False))
        with self._lock:
            sk = self._sketches.get(key)
            return sk.to_dict() if sk is not None else None

    def merge_state(self, state: Dict,
                    tags: Optional[Dict[str, str]] = None) -> None:
        """Fold a serialized sketch (another process's
        :meth:`sketch_state`) into this series."""
        incoming = QuantileSketch.from_dict(state)
        self._check_tags(tags)
        key = _tags(self._normalize_tags(tags))
        with self._lock:
            sk = self._sketches.get(key)
            if sk is None:
                self._sketches[key] = incoming
            else:
                sk.merge(incoming)

    def _prom_lines(self, exemplars: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} summary"
        # Render UNDER the lock (into a list, so the lock is not held
        # across yields): quantile() walks the sketch's sorted bins, and
        # a concurrent observe would mutate the dict mid-walk.
        lines = []
        with self._lock:
            for key, sk in self._sketches.items():
                for q in self.quantiles:
                    # Repr trims the float the way Prometheus clients do
                    # (0.5 not 0.50000): the label value is an opaque
                    # string to the scraper but a float to dashboards.
                    t = key + (("quantile", repr(q)),)
                    lines.append(
                        f"{self.name}{_fmt_tags(t)} {sk.quantile(q)}"
                    )
                lines.append(f"{self.name}_sum{_fmt_tags(key)} {sk.sum()}")
                lines.append(
                    f"{self.name}_count{_fmt_tags(key)} {sk.count}"
                )
        yield from lines


class RollingWindow:
    """Exact rolling percentiles over the last N observations.

    .. deprecated:: PR 8
        Superseded by :class:`~ray_dynamic_batching_tpu.utils.sketch.
        QuantileSketch` on every hot-path call site (router/queue
        compliance signals): the sketch holds a guaranteed relative
        error over the WHOLE run, merges across shards, and reads in
        O(bins) instead of an O(n log n) sort under the queue lock.
        This shim survives one release for out-of-tree callers, then
        goes away.

    App-layer analogue of the reference's rolling p95/p99 queue stats
    (``293-project/src/scheduler.py:343-372``).
    """

    def __init__(self, maxlen: int = 1000):
        warnings.warn(
            "RollingWindow is deprecated (one release): use "
            "ray_dynamic_batching_tpu.utils.sketch.QuantileSketch — same "
            "observe/percentile/mean surface, bounded relative error, "
            "mergeable.",
            DeprecationWarning,
            stacklevel=2,
        )
        self._window: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._window.append(value)

    def percentile(self, p: float) -> float:
        with self._lock:
            data = sorted(self._window)
        if not data:
            return 0.0
        idx = min(len(data) - 1, max(0, math.ceil(p * len(data)) - 1))
        return data[idx]

    def mean(self) -> float:
        with self._lock:
            return (sum(self._window) / len(self._window)) if self._window else 0.0

    def __len__(self) -> int:
        with self._lock:
            return len(self._window)


def _escape_label(value: str) -> str:
    # Prometheus exposition requires \\, \", \n escaping in label values.
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_tags(tags: TagMap) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in tags)
    return "{" + inner + "}"


class MetricsRegistry:
    """Process-wide registry; renders the Prometheus text format."""

    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        # Same rank as the per-metric locks: the registry snapshots and
        # releases before touching any Metric (the PR-8 fix), so the two
        # are never held together.
        self._lock = OrderedLock("metrics")

    def register(self, metric: Metric) -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(
                    f"metric {metric.name!r} already registered; reuse the "
                    "existing instance (duplicate registration would silently "
                    "drop the earlier metric's data from export)"
                )
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def prometheus_text(self) -> str:
        """Classic Prometheus 0.0.4 text exposition (no exemplars)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m._prom_lines())
        return "\n".join(lines) + "\n"

    def openmetrics_text(self) -> str:
        """OpenMetrics exposition WITH exemplars and the `# EOF` trailer.
        Served when the scraper negotiates ``application/openmetrics-text``
        via Accept — only that grammar permits exemplar suffixes."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m._prom_lines(exemplars=True))
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


def now_ms() -> float:
    return time.monotonic() * 1000.0
