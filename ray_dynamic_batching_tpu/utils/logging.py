"""Structured logging setup (analogue of the reference's spdlog/util logging)."""

from __future__ import annotations

import logging
import os
import sys

_FMT = "%(asctime)s %(levelname)s %(name)s :: %(message)s"
_configured = False


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("RDB_LOG_LEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FMT))
        root = logging.getLogger("rdb")
        root.setLevel(level)
        if not root.handlers:
            root.addHandler(handler)
        root.propagate = False
        _configured = True
    return logging.getLogger(f"rdb.{name}")
