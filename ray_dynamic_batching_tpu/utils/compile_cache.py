"""Persistent XLA compilation cache (SURVEY §7 hard-part (a)).

Every new (model, batch, seq) bucket pays a 20-40 s XLA compile on the
tunneled TPU; the reference never faces this because any CUDA batch size is
instantly runnable (``293-project/profiling/ModelProfiler.py:46``). JAX's
persistent compilation cache turns repeat compiles — across processes,
restarts, and profile sweeps — into disk hits. This module is the single
switch: every compile-heavy entry point (model host, decode engine,
profiler, bench) calls :func:`maybe_enable` before its first jit.

Enable with ``RDB_COMPILATION_CACHE_DIR=/path`` (or config override); ""
keeps it off.
"""

from __future__ import annotations

import threading

from ray_dynamic_batching_tpu.utils.config import get_config
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("compile_cache")

_lock = threading.Lock()
_applied: str | None = None


def maybe_enable() -> bool:
    """Idempotently point JAX at the configured persistent cache dir.
    Returns True when a cache is active. Safe to call before or after
    backend initialization (the knobs are read at compile time)."""
    global _applied
    cache_dir = get_config().compilation_cache_dir
    with _lock:
        if not cache_dir or _applied == cache_dir:
            return _applied is not None
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every program: the default min-entry-size/compile-time
        # gates would skip exactly the small decode-step programs the
        # serving path dispatches hottest.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        _applied = cache_dir
        logger.info("persistent compilation cache at %s", cache_dir)
        return True
