"""Typed, env-overridable framework configuration.

TPU-native analogue of the reference's ``RayConfig`` flag system
(``src/ray/common/ray_config_def.h:23`` — 218 ``RAY_CONFIG(type, name, default)``
entries overridable via ``RAY_<name>`` env vars). Here every field of
:class:`RDBConfig` is overridable via ``RDB_<NAME>`` environment variables, with
type coercion derived from the dataclass annotation.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Optional


def _coerce(value: str, typ: Any) -> Any:
    if typ is bool:
        return value.strip().lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    if typ is str:
        return value
    # Optional[X] / unions: try int, float, fall back to str.
    for t in (int, float):
        try:
            return t(value)
        except ValueError:
            continue
    return value


@dataclasses.dataclass
class RDBConfig:
    """All framework knobs in one place. Override any field with ``RDB_<NAME>``.

    Grouped the way the reference groups ``ray_config_def.h``: scheduling,
    batching, memory, control-plane timing, transport, observability, testing.
    """

    # --- scheduling (ref: 293-project/src/scheduler.py:28, nexus.py:154) ---
    # SLO safety divisor applied at schedule time (ref SLO_hack=2.2, scheduler.py:28).
    slo_safety_factor: float = 2.2
    # Fraction of the (safety-adjusted) SLO a saturated batch may spend computing
    # (Nexus "SLO/2" rule, nexus.py:154).
    slo_compute_fraction: float = 0.5
    # Rate-change fraction that triggers a reschedule (ref scheduler.py:794).
    rate_change_threshold: float = 0.05
    # Multiplier on the threshold for rate *decreases* (ref scheduler.py:798-801).
    rate_decrease_multiplier: float = 2.0
    # Seconds between control-loop monitoring passes (ref monitoring_interval=5).
    monitoring_interval_s: float = 5.0
    # Sliding window for request-rate estimation (ref RequestTracker window).
    rate_window_s: float = 10.0
    # Cold-window replan guard: suppress rate-change replans for models whose
    # sliding window covers fewer than this many seconds (a half-filled window
    # under-reads by up to 1/span and the monitor scales DOWN during rampup).
    # 0.0 = react immediately (the reference's behavior).
    rate_min_span_s: float = 0.0

    # --- batching / bucketing (TPU-first: XLA compiles per shape bucket) ---
    # Batch buckets are rounded up to the nearest of these (powers of two by
    # default keep the jit cache small; profile rows exist per bucket).
    max_batch_size: int = 1024
    # Opportunistic batching defaults (ref serve/batching.py:530).
    default_batch_wait_timeout_s: float = 0.01
    default_max_batch_size: int = 32
    # Sequence buckets for LLM prefill (powers of two from min upward).
    min_seq_bucket: int = 32
    max_seq_len: int = 8192

    # --- memory (HBM replaces the reference's gpu_mem budget, nexus.py:156) ---
    # Per-chip HBM budget in bytes (v5e = 16 GiB; leave headroom for XLA scratch).
    hbm_budget_bytes: int = 14 * 1024**3
    # Fraction of HBM the scheduler may plan against (scratch/fragmentation slack).
    hbm_plan_fraction: float = 0.9

    # --- compile management (no GPU analogue; XLA-specific) ---
    # Estimated cost charged to a migration that requires a fresh XLA compile.
    compile_cost_default_ms: float = 5000.0
    # Number of schedule intervals over which compile cost is amortized when
    # judging merge feasibility.
    compile_amortization_intervals: int = 60
    # Persistent compilation cache directory ("" disables).
    compilation_cache_dir: str = ""

    # --- queues (ref 293-project/src/scheduler.py:190) ---
    max_queue_len: int = 4096
    # Drop requests whose deadline cannot be met given profiled batch latency
    # (staleness discard, ref scheduler.py:281-283).
    discard_stale_requests: bool = True

    # --- control plane / runtime (ref: gcs health checks, ray_config_def.h:846) ---
    health_check_period_ms: int = 1000
    health_check_timeout_ms: int = 5000
    health_check_failure_threshold: int = 5
    actor_max_restarts: int = 3
    controller_checkpoint_period_s: float = 5.0

    # --- transport ---
    ingress_host: str = "0.0.0.0"
    ingress_port: int = 8265
    metrics_port: int = 9464

    # --- observability ---
    metrics_report_interval_s: float = 5.0
    slo_good_threshold: float = 0.98   # ref metrics_display.py:65
    slo_warn_threshold: float = 0.95

    # --- testing / chaos (ref: src/ray/rpc/rpc_chaos.cc:32) ---
    # Format: "method=N[,method=N...]" — fail the first N calls of `method`.
    testing_rpc_failure: str = ""
    # Deterministic seed for chaos injection.
    chaos_seed: int = 0

    @classmethod
    def from_env(cls, **overrides: Any) -> "RDBConfig":
        import typing

        hints = typing.get_type_hints(cls)  # resolves PEP 563 string annotations
        kwargs: dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            env_key = "RDB_" + f.name.upper()
            if env_key in os.environ:
                try:
                    kwargs[f.name] = _coerce(os.environ[env_key], hints[f.name])
                except ValueError as e:
                    raise ValueError(f"bad value for {env_key}: {e}") from e
        kwargs.update(overrides)
        return cls(**kwargs)


_global_config: Optional[RDBConfig] = None
_lock = threading.Lock()


def get_config() -> RDBConfig:
    """Process-wide config singleton (env-initialized on first use)."""
    global _global_config
    if _global_config is None:
        with _lock:
            if _global_config is None:
                _global_config = RDBConfig.from_env()
    return _global_config


def set_config(cfg: RDBConfig) -> None:
    global _global_config
    with _lock:
        _global_config = cfg


def reset_config() -> None:
    global _global_config
    with _lock:
        _global_config = None
