"""Shared pytree path utilities.

Single owner of the path→string convention used by param sharding rules
(models/base.py), pipeline layer stacking (parallel/pipeline.py), and
checkpoint keys (runtime/checkpoint.py) — these must stay byte-identical
or checkpoint keys stop matching partition-spec paths.
"""

from __future__ import annotations

from typing import Any, Dict

import jax


def path_str(path) -> str:
    """'/'-joined key path for a tree_flatten_with_path entry."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def flatten_with_paths(tree: Any) -> Dict[str, Any]:
    """Ordered {path_str: leaf} (flatten order); raises on key collisions
    (e.g. {'a': {'b': ...}, 'a/b': ...} both stringify to 'a/b' — silent
    merging would corrupt checkpoints)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: Dict[str, Any] = {}
    for path, leaf in flat:
        key = path_str(path)
        if key in out:
            raise ValueError(
                f"pytree path collision: two leaves stringify to {key!r}"
            )
        out[key] = leaf
    return out
