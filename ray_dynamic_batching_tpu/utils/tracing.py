"""Lightweight span tracing with context propagation.

Analogue of the reference's OpenTelemetry task/actor tracing
(``python/ray/util/tracing/tracing_helper.py:293,326,411`` — spans injected
around every call, context carried in task metadata via ``_DictPropagator``).
Here spans are in-process dataclasses with dict-based propagation so they can
cross actor mailboxes and HTTP hops; an exporter hook collects finished spans.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

# Process-unique random ids: a per-process counter would collide when spans
# from multiple workers are aggregated by one exporter.
def _new_span_id() -> int:
    return random.getrandbits(63)


_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "rdb_current_span", default=None
)

# Finished spans kept in-process are bounded; the exporter is the durable sink.
_FINISHED_SPAN_CAP = 10_000


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: int
    parent_id: Optional[int]
    start_ms: float
    end_ms: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)

    def duration_ms(self) -> float:
        return (self.end_ms or time.monotonic() * 1000.0) - self.start_ms


class Tracer:
    def __init__(self) -> None:
        self._finished: deque = deque(maxlen=_FINISHED_SPAN_CAP)
        self._lock = threading.Lock()
        self._exporter: Optional[Callable[[Span], None]] = None
        self.enabled = False

    def set_exporter(self, exporter: Callable[[Span], None]) -> None:
        self._exporter = exporter
        self.enabled = True

    def reset(self) -> None:
        """Disable tracing and drop exporter + buffered spans (test hygiene)."""
        self._exporter = None
        self.enabled = False
        self.clear()

    @contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[Optional[Span]]:
        if not self.enabled:
            yield None
            return
        parent = _current_span.get()
        s = Span(
            name=name,
            trace_id=parent.trace_id if parent else uuid.uuid4().hex,
            span_id=_new_span_id(),
            parent_id=parent.span_id if parent else None,
            start_ms=time.monotonic() * 1000.0,
            attributes=dict(attributes),
        )
        token = _current_span.set(s)
        try:
            yield s
        finally:
            s.end_ms = time.monotonic() * 1000.0
            _current_span.reset(token)
            with self._lock:
                self._finished.append(s)
            if self._exporter:
                self._exporter(s)

    # --- context propagation (ref: _DictPropagator, tracing_helper.py:165) ---
    def inject_context(self) -> Dict[str, Any]:
        s = _current_span.get()
        if s is None:
            return {}
        return {"trace_id": s.trace_id, "parent_span_id": s.span_id}

    @contextmanager
    def attach_context(self, ctx: Dict[str, Any], name: str) -> Iterator[Optional[Span]]:
        if not self.enabled or not ctx:
            with self.span(name):
                yield _current_span.get()
            return
        s = Span(
            name=name,
            trace_id=ctx.get("trace_id", uuid.uuid4().hex),
            span_id=_new_span_id(),
            parent_id=ctx.get("parent_span_id"),
            start_ms=time.monotonic() * 1000.0,
        )
        token = _current_span.set(s)
        try:
            yield s
        finally:
            s.end_ms = time.monotonic() * 1000.0
            _current_span.reset(token)
            with self._lock:
                self._finished.append(s)
            if self._exporter:
                self._exporter(s)

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


_tracer = Tracer()


def tracer() -> Tracer:
    return _tracer
