"""Lightweight span tracing with context propagation and span links.

Analogue of the reference's OpenTelemetry task/actor tracing
(``python/ray/util/tracing/tracing_helper.py:293,326,411`` — spans injected
around every call, context carried in task metadata via ``_DictPropagator``).
Here spans are in-process dataclasses with dict-based propagation so they can
cross actor mailboxes and HTTP hops; an exporter hook collects finished spans.

Beyond parent/child, spans carry **links** (OTel span links): dynamic
batching fans N request traces into ONE batch execution, which parent/child
cannot express — the batch span links to every member request span and each
member's execution span links back to the batch. HTTP/gRPC ingest honors
inbound W3C ``traceparent`` headers (:func:`parse_traceparent`), and
:func:`format_traceparent` mints one for clients that want to originate the
trace — there is no downstream HTTP hop here to forward it to.
"""

from __future__ import annotations

import contextvars
import random
import re
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

# Process-unique random ids: a per-process counter would collide when spans
# from multiple workers are aggregated by one exporter.
def _new_span_id() -> int:
    return random.getrandbits(63)


_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "rdb_current_span", default=None
)

# Finished spans kept in-process are bounded; the exporter is the durable sink.
_FINISHED_SPAN_CAP = 10_000

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: int
    parent_id: Optional[int]
    start_ms: float
    end_ms: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    # Span links (fan-in/fan-out across traces): each entry is a context
    # dict {"trace_id": str, "span_id": int} of the linked span.
    links: List[Dict[str, Any]] = field(default_factory=list)

    def duration_ms(self) -> float:
        return (self.end_ms or time.monotonic() * 1000.0) - self.start_ms

    def context(self) -> Dict[str, Any]:
        """Propagation/link context naming THIS span as the peer."""
        return {"trace_id": self.trace_id, "parent_span_id": self.span_id}


def link_to(span_or_ctx: Any) -> Optional[Dict[str, Any]]:
    """Normalize a Span or a propagated context dict into a link entry.
    Returns None for empty/contextless inputs so callers can filter."""
    if span_or_ctx is None:
        return None
    if isinstance(span_or_ctx, Span):
        return {"trace_id": span_or_ctx.trace_id, "span_id": span_or_ctx.span_id}
    trace_id = span_or_ctx.get("trace_id")
    span_id = span_or_ctx.get("parent_span_id", span_or_ctx.get("span_id"))
    if not trace_id or span_id is None:
        return None
    return {"trace_id": trace_id, "span_id": span_id}


def format_traceparent(ctx: Dict[str, Any]) -> Optional[str]:
    """W3C traceparent header from a propagated context (version 00,
    sampled flag set — this tracer records everything it is handed)."""
    link = link_to(ctx)
    if link is None:
        return None
    return f"00-{link['trace_id']}-{link['span_id']:016x}-01"


def parse_traceparent(header: Optional[str]) -> Dict[str, Any]:
    """Propagated context from a ``traceparent`` header; {} on absent or
    malformed input (a bad header must start a fresh trace, not error).
    The all-zero trace/span ids are invalid per W3C — honoring them would
    merge every unsampled client's requests into one degenerate trace."""
    if not header:
        return {}
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None or m.group(1) == "ff":
        return {}
    if set(m.group(2)) == {"0"} or set(m.group(3)) == {"0"}:
        return {}
    return {"trace_id": m.group(2), "parent_span_id": int(m.group(3), 16)}


class Tracer:
    def __init__(self) -> None:
        self._finished: deque = deque(maxlen=_FINISHED_SPAN_CAP)
        self._lock = threading.Lock()
        self._exporter: Optional[Callable[[Span], None]] = None
        self._export_error_logged = False
        self.enabled = False

    def set_exporter(self, exporter: Callable[[Span], None]) -> None:
        self._exporter = exporter
        self._export_error_logged = False
        self.enabled = True

    def reset(self) -> None:
        """Disable tracing and drop exporter + buffered spans (test hygiene)."""
        self._exporter = None
        self.enabled = False
        self.clear()

    def _finish(self, s: Span) -> None:
        with self._lock:
            self._finished.append(s)
        exporter = self._exporter
        if exporter is None:
            return
        try:
            exporter(s)
        except Exception:  # noqa: BLE001 — a broken sink (disk full,
            # closed file) must degrade TRACING, never the serving path
            # that emitted the span (spans finish inside queue pops and
            # engine hot loops; a propagated error there drops already-
            # popped requests on the floor).
            if not self._export_error_logged:
                self._export_error_logged = True
                import logging

                logging.getLogger("rdb.tracing").exception(
                    "span exporter failed; further errors suppressed"
                )

    @contextmanager
    def span(
        self,
        name: str,
        links: Optional[List[Optional[Dict[str, Any]]]] = None,
        **attributes: Any,
    ) -> Iterator[Optional[Span]]:
        if not self.enabled:
            yield None
            return
        parent = _current_span.get()
        s = Span(
            name=name,
            trace_id=parent.trace_id if parent else uuid.uuid4().hex,
            span_id=_new_span_id(),
            parent_id=parent.span_id if parent else None,
            start_ms=time.monotonic() * 1000.0,
            attributes=dict(attributes),
            links=[l for l in (links or []) if l],
        )
        token = _current_span.set(s)
        try:
            yield s
        finally:
            s.end_ms = time.monotonic() * 1000.0
            _current_span.reset(token)
            self._finish(s)

    # --- context propagation (ref: _DictPropagator, tracing_helper.py:165) ---
    def inject_context(self) -> Dict[str, Any]:
        s = _current_span.get()
        if s is None:
            return {}
        return {"trace_id": s.trace_id, "parent_span_id": s.span_id}

    def current_span(self) -> Optional[Span]:
        return _current_span.get()

    def current_trace_id(self) -> Optional[str]:
        """Trace id of the active span (metrics exemplars hook)."""
        s = _current_span.get()
        return s.trace_id if s is not None else None

    @contextmanager
    def attach_context(
        self,
        ctx: Dict[str, Any],
        name: str,
        links: Optional[List[Optional[Dict[str, Any]]]] = None,
        **attributes: Any,
    ) -> Iterator[Optional[Span]]:
        if not self.enabled or not ctx:
            with self.span(name, links=links, **attributes):
                yield _current_span.get()
            return
        s = Span(
            name=name,
            trace_id=ctx.get("trace_id", uuid.uuid4().hex),
            span_id=_new_span_id(),
            parent_id=ctx.get("parent_span_id"),
            start_ms=time.monotonic() * 1000.0,
            attributes=dict(attributes),
            links=[l for l in (links or []) if l],
        )
        token = _current_span.set(s)
        try:
            yield s
        finally:
            s.end_ms = time.monotonic() * 1000.0
            _current_span.reset(token)
            self._finish(s)

    def record_span(
        self,
        name: str,
        ctx: Optional[Dict[str, Any]] = None,
        start_ms: Optional[float] = None,
        end_ms: Optional[float] = None,
        links: Optional[List[Optional[Dict[str, Any]]]] = None,
        **attributes: Any,
    ) -> Optional[Span]:
        """Emit an already-finished span for a retroactively-measured
        interval (queue wait, prefill): the duration was observed by
        timestamps on the request, not by code running inside a ``with``
        block, so there is nothing to wrap. Joined to ``ctx``'s trace when
        given, else parented under the current span."""
        if not self.enabled:
            return None
        now = time.monotonic() * 1000.0
        parent = _current_span.get()
        if ctx:
            trace_id = ctx.get("trace_id", uuid.uuid4().hex)
            parent_id = ctx.get("parent_span_id")
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = uuid.uuid4().hex, None
        s = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            start_ms=start_ms if start_ms is not None else now,
            end_ms=end_ms if end_ms is not None else now,
            attributes=dict(attributes),
            links=[l for l in (links or []) if l],
        )
        self._finish(s)
        return s

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


_tracer = Tracer()


def tracer() -> Tracer:
    return _tracer
