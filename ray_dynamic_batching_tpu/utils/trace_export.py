"""Chrome-trace / Perfetto export for finished spans.

The reference exports OTel spans to whatever backend the operator wires up;
here the flight recorder renders spans in the Chrome trace-event JSON format
(the ``{"traceEvents": [...]}`` shape Perfetto and ``chrome://tracing`` both
open directly):

- one **process lane per component** (proxy, router, queue, engine, decode,
  replica, ...) derived from the span-name prefix;
- one **thread lane per chip/replica/model** inside the component, from the
  span's ``lane`` attribute when present;
- complete (``ph: "X"``) events carrying trace/span ids + attributes in
  ``args``;
- **flow arrows** (``ph: "s"``/``"f"``) rendering span links, so a batch
  execution visually connects to its N member request spans.

Two exporters feed this: :class:`ChromeTraceCollector` buffers spans
in-process (demos, tests), :class:`FileSpanExporter` appends one JSON object
per finished span to a JSONL file that ``tools/dump_trace.py`` converts
offline.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Iterable, List, Optional

from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.concurrency import assert_owner
from ray_dynamic_batching_tpu.utils.tracing import Span

# Spans a sink refused (cap reached, sink closed): counted per sink, and
# every export surface stamps ``truncated`` so a capped capture can never
# masquerade as a complete one (repo rule: no silent caps).
TRACE_DROPPED = m.Counter(
    "rdb_trace_dropped_spans_total",
    "Finished spans an export sink dropped (cap reached / sink closed)",
    tag_keys=("sink",),
)

# JSONL header sentinel key (first line of a FileSpanExporter capture).
_HEADER_KEY = "_rdb_export"
# Fixed header width: the line is written at open and REWRITTEN in place
# at close with the final counts, so it must occupy constant bytes.
_HEADER_WIDTH = 96

# Span-name prefix -> process lane. Unknown prefixes get their own lane
# appended after these, so new components never collapse into one row.
_COMPONENT_ORDER = (
    "proxy", "grpc", "handle", "router", "scheduler", "queue", "batch",
    "replica", "collate", "engine", "decode",
)


def span_component(span: Span) -> str:
    return span.name.split(".", 1)[0]


def span_to_dict(span: Span) -> Dict[str, Any]:
    return {
        "name": span.name,
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "parent_id": span.parent_id,
        "start_ms": span.start_ms,
        "end_ms": span.end_ms,
        "attributes": dict(span.attributes),
        "links": list(span.links),
    }


def span_from_dict(d: Dict[str, Any]) -> Span:
    return Span(
        name=d["name"],
        trace_id=d["trace_id"],
        span_id=int(d["span_id"]),
        parent_id=d.get("parent_id"),
        start_ms=float(d["start_ms"]),
        end_ms=d.get("end_ms"),
        attributes=dict(d.get("attributes") or {}),
        links=list(d.get("links") or []),
    )


def journal_to_chrome_events(
    events: Iterable[Dict[str, Any]],
    pid: int,
    lane: str = "paging",
) -> List[Dict[str, Any]]:
    """Paged-KV allocator journal entries (``engine/paging.
    PageEventJournal``) as Chrome trace events: one INSTANT event
    (``ph: "i"``) per alloc/free/CoW-copy/cache-reclaim/eviction, plus a
    ``kv_pages_in_use`` COUNTER track (``ph: "C"``) sampled at every
    event — time-aligned with the decode-turn spans because the journal
    stamps the same monotonic-ms clock the tracer uses."""
    out: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": lane}},
    ]
    for ev in events:
        args = {k: v for k, v in ev.items() if k != "t_ms"}
        out.append({
            "ph": "i", "s": "p", "name": ev["kind"], "cat": "paging",
            "pid": pid, "tid": 0,
            "ts": float(ev["t_ms"]) * 1000.0,
            "args": args,
        })
        if "pages_in_use" in ev:
            out.append({
                "ph": "C", "name": "kv_pages_in_use", "pid": pid, "tid": 0,
                "ts": float(ev["t_ms"]) * 1000.0,
                "args": {"pages": ev["pages_in_use"]},
            })
    return out


def to_chrome_trace(
    spans: Iterable[Span],
    journal: Optional[Iterable[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Render spans as a Chrome trace-event JSON document. ``journal``
    optionally appends a paged-KV allocator event lane
    (:func:`journal_to_chrome_events`) after the component lanes."""
    spans = [s for s in spans if s.end_ms is not None]
    components: List[str] = [
        c for c in _COMPONENT_ORDER
        if any(span_component(s) == c for s in spans)
    ]
    for s in spans:
        c = span_component(s)
        if c not in components:
            components.append(c)
    pid_of = {c: i + 1 for i, c in enumerate(components)}

    # Thread lanes: per component, the distinct `lane` attributes (chip /
    # replica / model ids); spans without one share lane 0.
    tid_of: Dict[tuple, int] = {}
    events: List[Dict[str, Any]] = []
    for c in components:
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[c], "tid": 0,
            "args": {"name": c},
        })
    by_span_id = {s.span_id: s for s in spans}
    flow_seq = 0
    for s in spans:
        c = span_component(s)
        lane = str(s.attributes.get("lane", ""))
        key = (c, lane)
        if key not in tid_of:
            tid_of[key] = len([k for k in tid_of if k[0] == c])
            events.append({
                "ph": "M", "name": "thread_name",
                "pid": pid_of[c], "tid": tid_of[key],
                "args": {"name": lane or c},
            })
        args: Dict[str, Any] = {
            "trace_id": s.trace_id,
            "span_id": f"{s.span_id:x}",
        }
        if s.parent_id is not None:
            args["parent_id"] = f"{s.parent_id:x}"
        if s.links:
            args["links"] = [
                {"trace_id": l["trace_id"], "span_id": f"{l['span_id']:x}"}
                for l in s.links
            ]
        args.update(s.attributes)
        events.append({
            "ph": "X", "name": s.name,
            "pid": pid_of[c], "tid": tid_of[key],
            "ts": s.start_ms * 1000.0,            # trace-event ts is in us
            "dur": max(0.0, (s.end_ms - s.start_ms) * 1000.0),
            "args": args,
        })
        # Flow arrows for links whose peer is in this capture: start at the
        # linked span, finish at this one (the batch span "collects" its
        # member requests in the viewer).
        for l in s.links:
            peer = by_span_id.get(l.get("span_id"))
            if peer is None or peer.end_ms is None:
                continue
            flow_seq += 1
            pk = (span_component(peer), str(peer.attributes.get("lane", "")))
            events.append({
                "ph": "s", "id": flow_seq, "name": "link", "cat": "link",
                "pid": pid_of[span_component(peer)], "tid": tid_of.get(pk, 0),
                "ts": peer.start_ms * 1000.0,
            })
            events.append({
                "ph": "f", "id": flow_seq, "name": "link", "cat": "link",
                "bp": "e",
                "pid": pid_of[c], "tid": tid_of[key],
                "ts": s.start_ms * 1000.0 + 0.001,
            })
    if journal is not None:
        events.extend(
            journal_to_chrome_events(journal, pid=len(components) + 1)
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class ChromeTraceCollector:
    """In-process exporter: buffer finished spans, write one Chrome trace.

    Usage: ``tracer().set_exporter(collector.export)`` ... ``collector.
    write(path)``. Spans past ``cap`` are dropped — COUNTED in
    ``rdb_trace_dropped_spans_total{sink="collector"}`` and stamped into
    the trace header (``truncated``/``dropped_spans``), never silently.
    """

    def __init__(self, cap: int = 100_000) -> None:
        self._spans: List[Span] = []
        self._cap = cap
        self._dropped = 0
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) < self._cap:
                self._spans.append(span)
            else:
                self._dropped += 1
                TRACE_DROPPED.inc(tags={"sink": "collector"})

    @property
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def chrome_trace(self) -> Dict[str, Any]:
        with self._lock:
            spans, dropped = list(self._spans), self._dropped
        doc = to_chrome_trace(spans)
        # Top-level metadata rides the trace JSON (Perfetto ignores
        # unknown keys): a capped capture says so in its own header.
        doc["metadata"] = {"truncated": dropped > 0,
                           "dropped_spans": dropped}
        return doc

    def write(self, path: str) -> int:
        """Write the Chrome trace JSON; returns the span count."""
        doc = self.chrome_trace()
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(self.spans)


class FileSpanExporter:
    """Append-one-JSON-object-per-span exporter (JSONL): the durable sink
    for long runs — convert offline with ``tools/dump_trace.py``.

    Writes are buffered (flushed every ``flush_every`` spans and on
    close): export runs inside queue pops and engine hot loops, so a
    per-span fsync-ish flush would serialize producers on disk latency.
    The file is TRUNCATED per exporter instance: span timestamps are
    process-monotonic, so mixing captures from different runs would
    render a garbled timeline.

    The first line is a fixed-width export header
    (``{"_rdb_export": {...}}``), rewritten in place at close with the
    final span/dropped counts and a ``truncated`` flag: spans refused
    past ``max_spans`` (disk-bound runs) are counted there and in
    ``rdb_trace_dropped_spans_total{sink="jsonl"}`` — a capped capture
    announces itself to every downstream reader. Spans arriving AFTER
    close (straggling threads) are counted in the metric and the
    ``dropped`` property only: the on-disk header is final at close and
    cannot reflect them.
    """

    def __init__(self, path: str, flush_every: int = 64,
                 max_spans: int = 1_000_000) -> None:
        self.path = path
        self.flush_every = flush_every
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._f = open(path, "w")
        self._written = 0
        self._dropped = 0
        self._pending = 0
        with self._lock:
            self._f.write(self._header_line())

    def _header_line(self) -> str:
        assert_owner(self._lock)  # counts must not move mid-render
        body = json.dumps({_HEADER_KEY: {
            "truncated": self._dropped > 0,
            "spans": self._written,
            "dropped": self._dropped,
        }})
        if len(body) > _HEADER_WIDTH:  # pragma: no cover - counts are ints
            raise ValueError("export header overflowed its fixed width")
        return body + " " * (_HEADER_WIDTH - len(body)) + "\n"

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def export(self, span: Span) -> None:
        line = json.dumps(span_to_dict(span))
        with self._lock:
            if self._f.closed or self._written >= self.max_spans:
                # Late span from a straggling thread, or cap reached:
                # counted, stamped at close — never silent.
                self._dropped += 1
                TRACE_DROPPED.inc(tags={"sink": "jsonl"})
                return
            self._f.write(line + "\n")
            self._written += 1
            self._pending += 1
            if self._pending >= self.flush_every:
                self._f.flush()
                self._pending = 0

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                # Rewrite the fixed-width header with the final counts.
                self._f.flush()
                self._f.seek(0)
                self._f.write(self._header_line())
                self._f.close()


def read_export_header(path: str) -> Optional[Dict[str, Any]]:
    """The capture's export header ({truncated, spans, dropped}), or
    None for legacy/foreign captures without one."""
    with open(path) as f:
        first = f.readline().strip()
    if not first:
        return None
    try:
        d = json.loads(first)
    except ValueError:
        return None
    return d.get(_HEADER_KEY) if isinstance(d, dict) else None


def read_spans_jsonl(path: str) -> List[Span]:
    out: List[Span] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if _HEADER_KEY in d:
                continue  # export header/trailer, not a span
            out.append(span_from_dict(d))
    return out


def trace_summary(spans: Iterable[Span]) -> Dict[str, Any]:
    """Small human-facing digest: span/trace counts and per-component spans."""
    spans = list(spans)
    comps: Dict[str, int] = {}
    for s in spans:
        comps[span_component(s)] = comps.get(span_component(s), 0) + 1
    return {
        "spans": len(spans),
        "traces": len({s.trace_id for s in spans}),
        "links": sum(len(s.links) for s in spans),
        "components": comps,
    }
