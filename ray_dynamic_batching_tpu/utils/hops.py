"""Latency budget ledger: per-request hop decomposition of a span tree.

PR 1's flight recorder emits every hop span, but a span dump answers
"what happened" — not "where did THIS request's milliseconds go". This
module turns one request's trace (plus the batch/turn spans that link
into it) into an exhaustive, CONSERVING per-hop ledger:

    sum(hop durations) + unattributed == end-to-end      (asserted)

against a FIXED hop taxonomy, so budgets, drift reports, and regression
gates all speak the same hop names.

Taxonomy & attribution rule
---------------------------
Every span name maps (``SPAN_TO_HOP``) into one of the ordered hops in
``HOP_ORDER`` — front door to decode. The ledger window is the trace's
ROOT span (the request's end-to-end extent). Non-root spans are clipped
to the window and swept: each instant of the window attributes to the
DEEPEST covering hop (max taxonomy rank — a ``router.assign`` inside a
``handle.remote`` is router time, a ``failover`` window swallows the
re-dispatch's inner assign), producing non-overlapping durations that
tile the window exactly. Instants covered by NO non-root span are the
**unattributed residual** — the root span's own un-delegated work (parse,
response serialization) plus every instrumentation gap: page-table
refreshes between decode turns, host work between queue pop and step
dispatch, allocator evictions — precisely the "invisible between a
span's start and end" cost the budget ledger exists to surface. The
residual is explicit and budgetable, never silently dropped.

Linked spans (``batch.form``, ``engine.step``, ``decode.turn``) live in
their OWN traces — dynamic batching fans N requests into one execution,
which parent/child cannot express — and are joined here by following
span links one hop, exactly like ``tools/dump_trace.py --trace-id``.
From the request's wall-clock perspective the whole batch window is time
the request spent in that hop, so the full (clipped) interval counts.

Failover: a re-dispatched request carries a ``failover.redispatch`` span
(submit -> re-assign) that OUTRANKS ``router.assign``, so retry windows
— backoff included — attribute to the ``failover`` hop, never to an
innocent router.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch
from ray_dynamic_batching_tpu.utils.tracing import Span

# The residual's reserved name (a manifest may budget it like any hop).
UNATTRIBUTED = "unattributed"

# Hop -> the span names that feed it. Order IS the attribution rank:
# later (deeper-pipeline) hops win overlaps. "admission" and "failover"
# are the taxonomy's control-plane hops (token-bucket check at the front
# door; deadline-budgeted re-dispatch after a system failure).
HOP_SPANS: Dict[str, Tuple[str, ...]] = {
    "proxy.request": ("proxy.request", "grpc.predict", "grpc.predict_stream"),
    "handle.remote": ("handle.remote", "handle.remote_stream"),
    "admission.check": ("admission.check",),
    "router.assign": ("router.assign",),
    "failover": ("failover.redispatch",),
    "queue.wait": ("queue.wait",),
    "batch.form": ("batch.form",),
    "engine.step": ("engine.step", "engine.request", "replica.batch",
                    "replica.execute", "collate.batch"),
    "decode.prefill": ("decode.prefill",),
    "decode.turn": ("decode.turn",),
}

HOP_ORDER: Tuple[str, ...] = tuple(HOP_SPANS)
HOP_RANK: Dict[str, int] = {h: i for i, h in enumerate(HOP_ORDER)}

SPAN_TO_HOP: Dict[str, str] = {
    name: hop for hop, names in HOP_SPANS.items() for name in names
}

# Root span names that mark a trace as a full request flight record
# (front door or handle): only these yield ledgers whose window IS the
# request's end-to-end latency.
FRONT_DOOR_SPANS = frozenset(
    HOP_SPANS["proxy.request"] + HOP_SPANS["handle.remote"]
)

# Hops that exist only on the dispatch path: a ledger containing none
# of these never reached a queue. Front-door spans wrap EVERYTHING the
# proxy serves — admission 429s, 404 route misses, /metrics scrapes —
# and those sub-ms "requests" must not be graded as request latency
# (during an overload most captures traces would be rejects, diluting
# every percentile toward zero and poisoning a ratchet).
DISPATCH_HOPS = frozenset(
    ("queue.wait", "batch.form", "engine.step",
     "decode.prefill", "decode.turn")
)

# Conservation tolerance: the sweep tiles the window exactly, so any
# disagreement is float summation noise — a millisecond ledger that is
# off by more than a nanosecond-scale epsilon has a real bug.
_EPSILON_MS = 1e-6


class LedgerError(AssertionError):
    """The ledger failed to conserve (sum(hops) + residual != e2e) or
    produced a negative hop — a decomposer bug, surfaced loudly; a
    budget gate built on a leaky ledger proves nothing."""


@dataclass
class HopLedger:
    """One request's conserving latency decomposition."""

    trace_id: str
    root: str                      # root span name (the window's owner)
    start_ms: float
    end_ms: float
    hops: Dict[str, float] = field(default_factory=dict)
    unattributed_ms: float = 0.0
    # The root span's attributes (HTTP code, route, …): the budget gate
    # uses them to grade only SERVED requests — a 429 reject or a
    # /metrics scrape also rides a front-door span, and its sub-ms
    # "latency" would dilute every percentile it sneaks into.
    root_attributes: Dict[str, Any] = field(default_factory=dict)
    # Mapped span time falling OUTSIDE the root window (e.g. decode
    # turns of a stream whose handle span closed at assign time) —
    # informational, excluded from conservation by definition.
    outside_window_ms: float = 0.0

    @property
    def end_to_end_ms(self) -> float:
        return self.end_ms - self.start_ms

    def check(self) -> None:
        """Assert the conservation contract. Never skipped, never
        silently clamped: Sigma(hops) + residual == end-to-end, every
        hop and the residual >= 0."""
        for hop, dur in self.hops.items():
            if dur < 0.0:
                raise LedgerError(
                    f"trace {self.trace_id}: negative hop {hop} = {dur} ms"
                )
        if self.unattributed_ms < -_EPSILON_MS:
            raise LedgerError(
                f"trace {self.trace_id}: negative residual "
                f"{self.unattributed_ms} ms"
            )
        total = sum(self.hops.values()) + self.unattributed_ms
        e2e = self.end_to_end_ms
        tol = _EPSILON_MS * max(1.0, abs(e2e))
        if abs(total - e2e) > tol:
            raise LedgerError(
                f"trace {self.trace_id}: ledger does not conserve — "
                f"sum(hops)+residual = {total} ms vs end-to-end {e2e} ms "
                f"(delta {total - e2e} ms)"
            )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "root": self.root,
            "start_ms": self.start_ms,
            "end_ms": self.end_ms,
            "end_to_end_ms": self.end_to_end_ms,
            "hops": {h: self.hops[h] for h in HOP_ORDER if h in self.hops},
            UNATTRIBUTED: self.unattributed_ms,
            "outside_window_ms": self.outside_window_ms,
        }


def _find_root(spans: Sequence[Span]) -> Optional[Span]:
    """The trace's root: a span whose parent is absent from the capture
    (``parent_id`` None, or pointing at an uncaptured span — an inbound
    ``traceparent`` names the CLIENT's span as parent). Earliest start
    wins among candidates; ties take the longest extent."""
    ids = {s.span_id for s in spans}
    candidates = [
        s for s in spans
        if s.end_ms is not None
        and (s.parent_id is None or s.parent_id not in ids)
    ]
    if not candidates:
        return None
    return min(candidates, key=lambda s: (s.start_ms, -(s.end_ms - s.start_ms)))


def decompose(trace_spans: Sequence[Span],
              linked_spans: Sequence[Span] = (),
              require_front_door: bool = True) -> Optional[HopLedger]:
    """One trace's spans (+ spans from other traces that link into it)
    -> a conserving :class:`HopLedger`, or None when the trace has no
    usable root (``require_front_door=True`` additionally demands a
    front-door/handle root — a singleton ``queue.wait`` trace from an
    untraced load generator is not a request flight record).

    The returned ledger has ALREADY passed :meth:`HopLedger.check` —
    a non-conserving decomposition raises :class:`LedgerError` here,
    it does not return quietly.
    """
    root = _find_root(trace_spans)
    if root is None:
        return None
    if require_front_door and root.name not in FRONT_DOOR_SPANS:
        return None
    w_start, w_end = root.start_ms, root.end_ms
    # (rank, start, end) coverage intervals from every mapped NON-ROOT
    # span, clipped to the window. The root defines the window but does
    # not cover it: time only the root accounts for is the residual.
    intervals: List[Tuple[int, float, float]] = []
    outside = 0.0
    for s in list(trace_spans) + list(linked_spans):
        if s is root or s.end_ms is None:
            continue
        hop = SPAN_TO_HOP.get(s.name)
        if hop is None:
            continue
        start, end = max(s.start_ms, w_start), min(s.end_ms, w_end)
        outside += max(0.0, (s.end_ms - s.start_ms) - max(0.0, end - start))
        if end > start:
            intervals.append((HOP_RANK[hop], start, end))

    hops: Dict[str, float] = {}
    unattributed = 0.0
    # Boundary sweep: between consecutive boundaries the covering set is
    # constant; the deepest-ranked ACTIVE hop wins the slice. Per-rank
    # active counters instead of re-scanning every interval per slice —
    # a 4k-token generation links ~4k decode.turn spans into one trace,
    # and an O(intervals^2) sweep would spend minutes on one ledger.
    events: Dict[float, List[Tuple[int, int]]] = {}
    for rank, s, e in intervals:
        events.setdefault(s, []).append((rank, +1))
        events.setdefault(e, []).append((rank, -1))
    bounds = sorted({w_start, w_end} | set(events))
    active = [0] * len(HOP_ORDER)
    for lo, hi in zip(bounds, bounds[1:]):
        for rank, delta in events.get(lo, ()):
            active[rank] += delta
        if hi <= w_start or lo >= w_end:
            continue
        best = -1
        for rank in range(len(active) - 1, -1, -1):
            if active[rank] > 0:
                best = rank
                break
        if best < 0:
            unattributed += hi - lo
        else:
            hop = HOP_ORDER[best]
            hops[hop] = hops.get(hop, 0.0) + (hi - lo)

    ledger = HopLedger(
        trace_id=root.trace_id,
        root=root.name,
        start_ms=w_start,
        end_ms=w_end,
        hops=hops,
        unattributed_ms=max(0.0, unattributed),
        outside_window_ms=outside,
        root_attributes=dict(root.attributes),
    )
    ledger.check()
    return ledger


def _link_index(spans: Sequence[Span]) -> Dict[int, List[Span]]:
    """linked-target span_id -> the spans that link to it (the batch /
    turn spans fan-in via links; this reverses them in one pass)."""
    idx: Dict[int, List[Span]] = {}
    for s in spans:
        for l in s.links:
            sid = l.get("span_id")
            if sid is not None:
                idx.setdefault(sid, []).append(s)
    return idx


def request_ledgers(
    spans: Sequence[Span],
    require_front_door: bool = True,
) -> Tuple[List[HopLedger], int]:
    """Every request flight record in a capture -> its ledger.

    Returns ``(ledgers, skipped_traces)`` — skipped are traces with no
    qualifying root (load-generator singletons, batch-span traces);
    the count is returned, not swallowed, so a gate can report how much
    of the capture it actually graded.
    """
    by_trace: Dict[str, List[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    links = _link_index(spans)
    ledgers: List[HopLedger] = []
    skipped = 0
    for trace_id, mine in by_trace.items():
        linked: List[Span] = []
        seen = set()
        for s in mine:
            for peer in links.get(s.span_id, ()):
                if peer.trace_id != trace_id and peer.span_id not in seen:
                    seen.add(peer.span_id)
                    linked.append(peer)
        ledger = decompose(mine, linked,
                           require_front_door=require_front_door)
        if ledger is None:
            skipped += 1
        else:
            ledgers.append(ledger)
    ledgers.sort(key=lambda l: (l.start_ms, l.trace_id))
    return ledgers, skipped


def is_served(ledger: "HopLedger") -> bool:
    """True when the ledger describes a DISPATCHED request — the only
    kind whose latency a TTFT budget grades. Excludes error/reject
    roots (HTTP ``code`` attribute outside 2xx) and ledgers that never
    touched a dispatch hop (admission rejects, 404s, metrics scrapes —
    all of which ride front-door spans too)."""
    code = str(ledger.root_attributes.get("code", "") or "")
    if code and not code.startswith("2"):
        return False
    return any(h in ledger.hops for h in DISPATCH_HOPS)


def hop_sketches(
    ledgers: Iterable[HopLedger],
    relative_accuracy: float = 0.01,
) -> Dict[str, QuantileSketch]:
    """Per-hop mergeable quantile sketches over a set of ledgers (the
    residual included under :data:`UNATTRIBUTED`, end-to-end under
    ``end_to_end`` — both budgetable)."""
    out: Dict[str, QuantileSketch] = {}

    def _observe(name: str, value: float) -> None:
        sk = out.get(name)
        if sk is None:
            sk = out[name] = QuantileSketch(
                relative_accuracy=relative_accuracy
            )
        sk.observe(max(0.0, value))

    for ledger in ledgers:
        for hop, dur in ledger.hops.items():
            _observe(hop, dur)
        _observe(UNATTRIBUTED, ledger.unattributed_ms)
        _observe("end_to_end", ledger.end_to_end_ms)
    return out


def format_ledger_table(ledgers: Sequence[HopLedger]) -> str:
    """Terminal table: one row per request, one column per hop present
    in the set (plus residual and end-to-end) — ``tools/dump_trace.py
    --hops``."""
    present = [h for h in HOP_ORDER if any(h in l.hops for l in ledgers)]
    cols = present + [UNATTRIBUTED, "e2e_ms"]
    head = f"{'trace':<14} {'root':<20}" + "".join(
        f" {c:>14}" for c in cols
    )
    lines = [head, "-" * len(head)]
    for l in ledgers:
        row = f"{l.trace_id[:12]:<14} {l.root:<20}"
        for h in present:
            row += f" {l.hops.get(h, 0.0):>14.2f}"
        row += f" {l.unattributed_ms:>14.2f} {l.end_to_end_ms:>14.2f}"
        lines.append(row)
    return "\n".join(lines)
