"""Scheduler decision audit log — a bounded structured ring of replans.

The reference logs schedule changes as free-text lines (``293-project/src/
scheduler.py:46-86``); operators debugging a live rebalance need structure:
WHAT triggered the decision (rate delta, health event, quarantine), what the
scheduler SAW (observed rates, profile rows consulted), what CHANGED
(old -> new plan diff), and what the move COST (compile + weight-upload
weighted transfer cost / engines moved). Every control plane writes
:class:`AuditRecord` entries into one of these rings; ``snapshot()`` /
``ServeController.status()`` / the dashboard's audit panel read them back.

The ring is bounded (default 256) so a chatty monitor can never grow the
control plane's memory; it is the in-process analogue of the reference's
metrics.json history, but queryable and diff-shaped.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class AuditRecord:
    """One control-plane decision, diff-shaped."""

    seq: int                    # monotonically increasing per ring
    wall_time: float            # time.time() at the decision
    domain: str                 # "nexus" | "llm" | "serve" | "frontdoor"
    trigger: str                # "manual" | "rate_change" | "quarantine" |
                                # "heal" | "rolling_update" | "scale" |
                                # "store_fenced" | "failover_adopt" |
                                # "admission_drift" | ...
    key: str = ""               # deployment/model the decision is about
                                # ("" = domain-wide, e.g. a full replan)
    observed: Dict[str, Any] = field(default_factory=dict)   # inputs seen
    inputs: Dict[str, Any] = field(default_factory=dict)     # rows consulted
    before: Any = None          # old plan / state (JSON-safe)
    after: Any = None           # new plan / state (JSON-safe)
    diff: Dict[str, Any] = field(default_factory=dict)       # old -> new
    migration_cost: float = 0.0
    note: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "wall_time": self.wall_time,
            "domain": self.domain,
            "trigger": self.trigger,
            "key": self.key,
            "observed": self.observed,
            "inputs": self.inputs,
            "before": self.before,
            "after": self.after,
            "diff": self.diff,
            "migration_cost": self.migration_cost,
            "note": self.note,
        }


class AuditLog:
    """Thread-safe bounded ring of :class:`AuditRecord`.

    ``now`` injects the decision timestamp source (default wall clock):
    the what-if simulator (``sim/``) passes its virtual clock so replayed
    replans carry VIRTUAL timestamps and the dashboard timeline renders a
    simulated run identically to a live one. Live callers are unchanged.
    """

    def __init__(self, domain: str, capacity: int = 256,
                 now=time.time) -> None:
        self.domain = domain
        self._ring: deque = deque(maxlen=capacity)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._now = now

    def record(self, trigger: str, **fields: Any) -> AuditRecord:
        rec = AuditRecord(
            seq=next(self._seq),
            wall_time=self._now(),
            domain=self.domain,
            trigger=trigger,
            **fields,
        )
        with self._lock:
            self._ring.append(rec)
        return rec

    def records(
        self, key: Optional[str] = None, last: Optional[int] = None
    ) -> List[AuditRecord]:
        with self._lock:
            out = list(self._ring)
        if key is not None:
            out = [r for r in out if r.key == key or r.key == ""]
        if last is not None:
            out = out[-last:]
        return out

    def to_dicts(
        self, key: Optional[str] = None, last: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        return [r.to_dict() for r in self.records(key=key, last=last)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def plan_diff(
    before: List[Optional[List[str]]], after: List[Optional[List[str]]]
) -> Dict[str, Any]:
    """Old -> new placement diff over per-engine model lists: which engines
    changed, which models joined/left the serving set."""
    n = max(len(before), len(after))
    before = list(before) + [None] * (n - len(before))
    after = list(after) + [None] * (n - len(after))
    changed = {}
    for i, (b, a) in enumerate(zip(before, after)):
        b, a = sorted(b or []), sorted(a or [])
        if b != a:
            changed[str(i)] = {"old": b, "new": a}
    all_before = {m for b in before for m in (b or [])}
    all_after = {m for a in after for m in (a or [])}
    return {
        "engines_changed": changed,
        "models_added": sorted(all_after - all_before),
        "models_removed": sorted(all_before - all_after),
    }
