"""Live scheduler — rate monitoring, rebalance, minimal-movement migration.

Re-creates the reference's ``NexusScheduler`` control plane
(``293-project/src/scheduler.py:602-929``): a monitoring loop samples per-model
request rates every interval (:763), re-runs squishy bin packing when a rate
moves past the threshold (5%, doubled for decreases — :794-801), then matches
old→new node plans to minimize model movement (:857-891) and pushes the new
(sessions, duty-cycle) to each worker's update channel (:906-929).

TPU-first difference: a "transfer" costs a weight upload **plus an XLA
compile** for every (model, bucket) the target engine hasn't compiled, so the
matcher's objective is weighted by profile-measured compile_ms + HBM bytes
instead of a flat transfer count (SURVEY.md §7 stage 5).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ray_dynamic_batching_tpu.engine.queue import QueueManager
from ray_dynamic_batching_tpu.engine.rates import RateRegistry
from ray_dynamic_batching_tpu.engine.request import Request
from ray_dynamic_batching_tpu.engine.worker import ReplicaEngine
from ray_dynamic_batching_tpu.profiles.table import BatchProfile
from ray_dynamic_batching_tpu.scheduler.audit import AuditLog, plan_diff
from ray_dynamic_batching_tpu.scheduler.nexus import (
    NodePlan,
    Session,
    SquishyBinPacker,
)
from ray_dynamic_batching_tpu.utils.config import get_config
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("control")

BRUTE_FORCE_LIMIT = 7  # assignment is brute-forced up to this many nodes


@dataclass
class ModelEntry:
    """Registered model contract (ref models_config, scheduler.py:30-35)."""

    name: str
    slo_ms: float
    seq_len: int = 0


def transfer_cost(
    engine_models: frozenset,
    plan: NodePlan,
    profiles: Dict[str, BatchProfile],
) -> float:
    """Cost of pointing an engine at ``plan``: for every model the engine
    doesn't already host, charge weight bytes (upload) + compile time."""
    cost = 0.0
    for p in plan.placements:
        name = p.session.model
        if name in engine_models:
            continue
        prof = profiles.get(name)
        if prof is None:
            cost += 1.0
            continue
        row = prof.row_for(p.batch_size, p.session.seq_len) or prof.bucket_for(
            p.batch_size, p.session.seq_len
        )
        compile_ms = row.compile_ms if row else 1000.0
        weight_mb = prof.weights_hbm_bytes() / 1e6
        cost += compile_ms + weight_mb  # ms-equivalent weighting
    return cost


def match_plans_to_engines(
    engine_models: List[frozenset],
    plans: List[NodePlan],
    profiles: Dict[str, BatchProfile],
) -> List[Optional[NodePlan]]:
    """Assign new node plans to engines minimizing total transfer cost.

    Brute-force over permutations for small counts (the reference's approach,
    scheduler.py:857-891), greedy best-match beyond BRUTE_FORCE_LIMIT.
    Returns, per engine, its new plan (None = engine idles).
    """
    n_engines = len(engine_models)
    padded: List[Optional[NodePlan]] = list(plans) + [None] * max(
        0, n_engines - len(plans)
    )
    if len(plans) > n_engines:
        logger.warning(
            "plan needs %d chips but only %d engines; truncating (capacity!)",
            len(plans), n_engines,
        )
        padded = list(plans[:n_engines])

    if n_engines <= BRUTE_FORCE_LIMIT:
        best: Optional[Tuple[float, Tuple[int, ...]]] = None
        for perm in itertools.permutations(range(n_engines)):
            cost = sum(
                transfer_cost(engine_models[e], padded[i], profiles)
                for i, e in enumerate(perm)
                if padded[i] is not None
            )
            if best is None or cost < best[0]:
                best = (cost, perm)
        assignment: List[Optional[NodePlan]] = [None] * n_engines
        for i, e in enumerate(best[1]):
            assignment[e] = padded[i]
        return assignment

    # Greedy: most expensive-to-move plans pick their cheapest engine first.
    order = sorted(
        [i for i, p in enumerate(padded) if p is not None],
        key=lambda i: -max(
            transfer_cost(m, padded[i], profiles) for m in engine_models
        ),
    )
    free = set(range(n_engines))
    assignment = [None] * n_engines
    for i in order:
        # Tie-break toward engines hosting fewer models so a zero-savings
        # plan lands on an empty engine instead of displacing a warm one.
        e = min(
            free,
            key=lambda e: (
                transfer_cost(engine_models[e], padded[i], profiles),
                len(engine_models[e]),
                e,
            ),
        )
        assignment[e] = padded[i]
        free.remove(e)
    return assignment


class LiveScheduler:
    """The running control plane for one scheduling domain."""

    def __init__(
        self,
        packer: SquishyBinPacker,
        engines: Sequence[ReplicaEngine],
        queues: Optional[QueueManager] = None,
        rates: Optional[RateRegistry] = None,
        metrics_path: Optional[str] = None,
        clock=time.monotonic,
    ):
        cfg = get_config()
        self.packer = packer
        self.engines = list(engines)
        self.queues = queues or QueueManager(max_len=cfg.max_queue_len)
        self.rates = rates or RateRegistry(window_s=cfg.rate_window_s)
        self.metrics_path = metrics_path
        self.monitoring_interval_s = cfg.monitoring_interval_s
        self.rate_threshold = cfg.rate_change_threshold
        self.rate_decrease_multiplier = cfg.rate_decrease_multiplier
        self._clock = clock
        self._models: Dict[str, ModelEntry] = {}
        self._current_plan: List[NodePlan] = []
        self._assignment: List[Optional[NodePlan]] = [None] * len(self.engines)
        self._lock = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.schedule_changes = 0
        self.schedule_log: List[Dict] = []
        # Structured replan ring: trigger, observed rates, profile rows
        # consulted, old->new diff, migration cost (scheduler/audit.py).
        self.audit = AuditLog("nexus")

    # --- registration (ref models_config) ---------------------------------
    def register_model(self, name: str, slo_ms: float, seq_len: int = 0) -> None:
        if name not in self.packer.profiles:
            raise KeyError(f"no batch profile for model {name!r}")
        self._models[name] = ModelEntry(name, slo_ms, seq_len)

    # --- ingress path (ref submit_request, scheduler.py:734-751) ----------
    def submit_request(self, request: Request) -> bool:
        entry = self._models.get(request.model)
        if entry is None:
            request.reject(KeyError(f"model {request.model!r} not registered"))
            return False
        # Record DEMAND before the enqueue outcome: if drops suppressed the
        # signal, an overloaded queue would read as a rate collapse and the
        # monitor would scale DOWN during overload (inverted feedback).
        self.rates.record(request.model)
        return self.queues.queue(request.model).add_request(request)

    # --- scheduling -------------------------------------------------------
    def _sessions_for(self, rates: Dict[str, float]) -> List[Session]:
        return [
            Session(
                model=e.name,
                slo_ms=e.slo_ms,
                rate_rps=rates.get(e.name, 0.0),
                seq_len=e.seq_len,
            )
            for e in self._models.values()
        ]

    def rebalance(
        self,
        rates: Optional[Dict[str, float]] = None,
        trigger: str = "manual",
    ) -> List[NodePlan]:
        """Re-run bin packing and migrate with minimal movement
        (ref _update_schedule, scheduler.py:834-929)."""
        with self._lock:
            rates = rates if rates is not None else self.rates.rates()
            plan = self.packer.plan(self._sessions_for(rates))
            engine_models = [
                frozenset(e.models) for e in self.engines
            ]
            assignment = match_plans_to_engines(
                engine_models, plan, self.packer.profiles
            )
            # Audit inputs BEFORE applying: the old assignment and the
            # per-engine cost of moving to the new one (the matcher's own
            # objective — compile_ms + weight-MB for models not resident).
            old_models = [sorted(m) for m in engine_models]
            new_models = [
                sorted(n.models) if n is not None else [] for n in assignment
            ]
            migration_cost = sum(
                transfer_cost(engine_models[e], n, self.packer.profiles)
                for e, n in enumerate(assignment)
                if n is not None
            )
            for engine, node_plan in zip(self.engines, assignment):
                if node_plan is not None:
                    engine.assign(node_plan)
                elif engine.models:
                    engine.assign(NodePlan())  # idle this engine
            self._current_plan = plan
            self._assignment = assignment
            self.rates.mark_scheduled(rates)
            self.schedule_changes += 1
            self.schedule_log.append(
                {
                    "ts": self._clock(),
                    "rates": dict(rates),
                    "nodes": [n.describe() for n in plan],
                }
            )
            self.audit.record(
                trigger,
                observed={"rates_rps": {k: round(v, 2)
                                        for k, v in rates.items()}},
                inputs={
                    # The profile rows the packer committed to: per
                    # placement, the (batch, latency) row that sized it.
                    "placements": [
                        {"model": p.session.model, "batch": p.batch_size,
                         "latency_ms": round(p.latency_ms, 2),
                         "occupancy": round(p.occupancy, 3)}
                        for n in plan for p in n.placements
                    ],
                },
                before=[", ".join(m) for m in old_models],
                after=[", ".join(m) for m in new_models],
                diff=plan_diff(old_models, new_models),
                migration_cost=round(migration_cost, 1),
            )
            logger.info(
                "rebalance #%d: %d nodes for rates %s",
                self.schedule_changes, len(plan),
                {k: round(v, 1) for k, v in rates.items()},
            )
            return plan

    # --- monitor loop (ref _monitor_request_rates, scheduler.py:763-801) --
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitoring_interval_s):
            try:
                changed = self.rates.changed_models(
                    self.rate_threshold, self.rate_decrease_multiplier
                )
                if changed:
                    logger.info("rate change detected: %s", changed)
                    self.rebalance(trigger="rate_change")
                if self.metrics_path:
                    self.write_metrics()
            except Exception:  # noqa: BLE001
                logger.exception("monitor iteration failed")

    def start_monitoring(self) -> None:
        if self._monitor is not None:
            return
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="rdb-monitor", daemon=True
        )
        self._monitor.start()

    def stop_monitoring(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None

    # --- observability (ref metrics.json writer, scheduler.py:969-983) ----
    def snapshot(self) -> Dict:
        return {
            "time": self._clock(),
            "rates_rps": self.rates.rates(),
            "scheduled_rates_rps": self.rates.scheduled_rates(),
            "queues": self.queues.stats(),
            "plan": [n.describe() for n in self._current_plan],
            "engines": [e.describe() for e in self.engines],
            "schedule_changes": self.schedule_changes,
            "audit": self.audit.to_dicts(last=20),
        }

    def write_metrics(self) -> None:
        with open(self.metrics_path, "w") as f:
            json.dump(self.snapshot(), f, indent=2)

    def render_status(self) -> str:
        """Terminal SLO status (ref metrics_display.py:42-66) — one table
        renderer for scheduler, state CLI, and dashboard alike."""
        from ray_dynamic_batching_tpu.state import render_queue_table

        return render_queue_table(self.queues.stats(), self.rates.rates())
