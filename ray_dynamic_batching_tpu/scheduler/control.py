"""Live scheduler — rate monitoring, rebalance, minimal-movement migration.

Re-creates the reference's ``NexusScheduler`` control plane
(``293-project/src/scheduler.py:602-929``): a monitoring loop samples per-model
request rates every interval (:763), re-runs squishy bin packing when a rate
moves past the threshold (5%, doubled for decreases — :794-801), then matches
old→new node plans to minimize model movement (:857-891) and pushes the new
(sessions, duty-cycle) to each worker's update channel (:906-929).

TPU-first difference: a "transfer" costs a weight upload **plus an XLA
compile** for every (model, bucket) the target engine hasn't compiled, so the
matcher's objective is weighted by profile-measured compile_ms + HBM bytes
instead of a flat transfer count (SURVEY.md §7 stage 5).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence

from ray_dynamic_batching_tpu.engine.queue import QueueManager
from ray_dynamic_batching_tpu.engine.rates import RateRegistry
from ray_dynamic_batching_tpu.engine.request import Request
from ray_dynamic_batching_tpu.engine.worker import ReplicaEngine
from ray_dynamic_batching_tpu.scheduler.audit import AuditLog
from ray_dynamic_batching_tpu.scheduler.nexus import (
    NodePlan,
    Session,
    SquishyBinPacker,
)

# The decide step is extracted to scheduler/replan.py (pure, clock-free,
# jax-free) so the what-if simulator (sim/) consumes the SAME logic this
# threaded path applies — re-exported here for existing importers.
from ray_dynamic_batching_tpu.scheduler.replan import (  # noqa: F401
    BRUTE_FORCE_LIMIT,
    ModelEntry,
    decide_replan,
    match_plans_to_engines,
    sessions_for,
    transfer_cost,
)
from ray_dynamic_batching_tpu.utils.config import get_config
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("control")


class LiveScheduler:
    """The running control plane for one scheduling domain."""

    def __init__(
        self,
        packer: SquishyBinPacker,
        engines: Sequence[ReplicaEngine],
        queues: Optional[QueueManager] = None,
        rates: Optional[RateRegistry] = None,
        metrics_path: Optional[str] = None,
        clock=time.monotonic,
    ):
        cfg = get_config()
        self.packer = packer
        self.engines = list(engines)
        self.queues = queues or QueueManager(max_len=cfg.max_queue_len)
        self.rates = rates or RateRegistry(window_s=cfg.rate_window_s)
        self.metrics_path = metrics_path
        self.monitoring_interval_s = cfg.monitoring_interval_s
        self.rate_threshold = cfg.rate_change_threshold
        self.rate_decrease_multiplier = cfg.rate_decrease_multiplier
        # Cold-window guard (rates.changed_models min_span_s): suppress
        # replans while the sliding window covers fewer than this many
        # seconds — a half-filled window under-reads rates by up to
        # 1/span and a monitor acting on it scales DOWN during rampup
        # (the inversion the LLM control loop already guards against).
        # Default 0.0 preserves the historical always-react behavior.
        self.rate_min_span_s = cfg.rate_min_span_s
        self._clock = clock
        self._models: Dict[str, ModelEntry] = {}
        self._current_plan: List[NodePlan] = []
        # Engines the monitor has already seen dead: the heal replan fires
        # once per death (a dead engine stays out of every later plan).
        self._dead_engines: set = set()
        self._lock = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.schedule_changes = 0
        self.schedule_log: List[Dict] = []
        # Structured replan ring: trigger, observed rates, profile rows
        # consulted, old->new diff, migration cost (scheduler/audit.py).
        self.audit = AuditLog("nexus")
        # Optional gray-health pricing hook (ISSUE 9): a callable
        # returning engine_id -> capacity factor (1.0 = full chip,
        # probation < 1, ejected 0 — but ejected engines should simply
        # report unhealthy). None = every alive engine is a full chip.
        # ``enable_gray_monitoring()`` wires it to a live detector fed
        # by per-batch step ratios; callers may install their own.
        self.capacity_factors = None
        self.gray = None
        self._gray_ejected: set = set()
        self._gray_window_ticks = 3
        self._gray_windows: Dict[str, List[List[float]]] = {}

    # --- registration (ref models_config) ---------------------------------
    def register_model(self, name: str, slo_ms: float, seq_len: int = 0,
                       mesh_shape: str = "1x1", spec: str = "off",
                       spec_acceptance: float = 0.0,
                       spec_tokens: int = 4,
                       prefill_chunk_ms: float = 0.0) -> None:
        """``mesh_shape`` is the model's preferred serving slice
        ("1x4" = a 4-chip TP replica priced from its mesh profile
        rows); replans degrade it to surviving geometry when the wide
        slices are gone (scheduler/replan.degrade_sessions).
        ``spec="on"`` prices the model from its spec profile rows at
        the PROFILED ``spec_acceptance`` (ISSUE 13; same ModelEntry
        surface as the sim scheduler — defaults byte-identical)."""
        if name not in self.packer.profiles:
            raise KeyError(f"no batch profile for model {name!r}")
        self._models[name] = ModelEntry(
            name, slo_ms, seq_len, mesh_shape, spec=spec,
            spec_acceptance=spec_acceptance, spec_tokens=spec_tokens,
            prefill_chunk_ms=prefill_chunk_ms,
        )

    # --- ingress path (ref submit_request, scheduler.py:734-751) ----------
    def submit_request(self, request: Request) -> bool:
        entry = self._models.get(request.model)
        if entry is None:
            request.reject(KeyError(f"model {request.model!r} not registered"))
            return False
        # Record DEMAND before the enqueue outcome: if drops suppressed the
        # signal, an overloaded queue would read as a rate collapse and the
        # monitor would scale DOWN during overload (inverted feedback).
        self.rates.record(request.model)
        return self.queues.queue(request.model).add_request(request)

    # --- scheduling -------------------------------------------------------
    def _sessions_for(self, rates: Dict[str, float]) -> List[Session]:
        return sessions_for(self._models, rates)

    @staticmethod
    def _engine_alive(engine) -> bool:
        """Duck-typed liveness: engines exposing ``healthy()`` (ReplicaEngine,
        sim/test fakes) are consulted; anything else counts alive."""
        probe = getattr(engine, "healthy", None)
        return bool(probe()) if callable(probe) else True

    def alive_engines(self) -> List[ReplicaEngine]:
        return [e for e in self.engines if self._engine_alive(e)]

    def rebalance(
        self,
        rates: Optional[Dict[str, float]] = None,
        trigger: str = "manual",
    ) -> List[NodePlan]:
        """Re-run bin packing and migrate with minimal movement
        (ref _update_schedule, scheduler.py:834-929). The DECISION —
        bin-pack, minimal-movement match, audit payload — is the shared
        pure function (``replan.decide_replan``); this method only reads
        rates and APPLIES the result to the live engines. Dead engines
        are excluded from packing and assignment — their queued work is
        in the shared per-model queues, so the surviving engines' new
        plans pick it up without an explicit drain."""
        with self._lock:
            rates = rates if rates is not None else self.rates.rates()
            alive = self.alive_engines()
            factors = None
            if self.capacity_factors is not None:
                by_id = self.capacity_factors()
                factors = [by_id.get(e.engine_id, 1.0) for e in alive]
            # Mesh-sliced engines advertise their chip-set width (an
            # engine without the attribute is one chip — the classic
            # domain, where these lists are all-1/"1x1" and the decision
            # is byte-identical to the pre-mesh planner). A slice death
            # removes its width here, so the heal replan runs over the
            # SURVIVING geometry and degrade_sessions re-shapes TP
            # models to the slices still standing.
            widths = [int(getattr(e, "width", 1) or 1) for e in alive]
            meshes = [
                str(getattr(e, "mesh_shape", "") or f"1x{w}")
                for e, w in zip(alive, widths)
            ]
            decision = decide_replan(
                self.packer,
                [frozenset(e.models) for e in alive],
                self._sessions_for(rates),
                rates,
                capacity_factors=factors,
                engine_widths=widths,
                engine_meshes=meshes,
            )
            for engine, node_plan in zip(alive, decision.assignment):
                if node_plan is not None:
                    engine.assign(node_plan)
                elif engine.models:
                    engine.assign(NodePlan())  # idle this engine
            self._current_plan = decision.plan
            self.rates.mark_scheduled(rates)
            self.schedule_changes += 1
            self.schedule_log.append(
                {
                    "ts": self._clock(),
                    "rates": dict(rates),
                    "nodes": [n.describe() for n in decision.plan],
                }
            )
            self.audit.record(trigger, **decision.audit_fields())
            logger.info(
                "rebalance #%d: %d nodes for rates %s",
                self.schedule_changes, len(decision.plan),
                {k: round(v, 1) for k, v in rates.items()},
            )
            return decision.plan

    # --- engine heal (the controller's unhealthy-replacement discipline,
    # applied to the scheduling domain: a dead engine's models migrate to
    # survivors instead of silently starving their queues) ----------------
    def check_engine_health(self) -> bool:
        """Detect newly dead engines; replan over survivors when found.
        Returns True when a heal replan fired. Heal bypasses the rate
        cold-window guard — it is failure-driven, not rate-driven."""
        newly_dead = [
            e for e in self.engines
            if e.engine_id not in self._dead_engines
            and not self._engine_alive(e)
        ]
        if not newly_dead:
            return False
        for e in newly_dead:
            self._dead_engines.add(e.engine_id)
            logger.warning(
                "engine %s dead; migrating its models to survivors",
                e.engine_id,
            )
        observed: Dict = {"dead_engines": sorted(self._dead_engines)}
        # Slice semantics (serve/failover.SliceDeadError): a multi-chip
        # engine dying means one chip in its gang took the whole slice
        # down — the audit names the lost width so the heal replan's
        # degraded shapes are explainable.
        slices = {
            e.engine_id: {"width": int(getattr(e, "width", 1) or 1)}
            for e in newly_dead
            if int(getattr(e, "width", 1) or 1) > 1
        }
        if slices:
            observed["dead_slices"] = slices
        self.audit.record(
            "engine_dead",
            observed=observed,
            diff={"removed": [e.engine_id for e in newly_dead]},
            note="engine death detected by monitor; replan over survivors",
        )
        self.rebalance(trigger="heal")
        return True

    # --- gray-failure detection (ISSUE 9: the LIVE producer for the
    # capacity_factors hook — the sim twin is SimScheduler.check_gray_health,
    # same detector, same grading rule, no drift) --------------------------
    def enable_gray_monitoring(self, policy=None,
                               window_ticks: int = 3) -> None:
        """Arm engine-level gray detection: per-batch observed/expected
        step ratios (ReplicaEngine.track_ratios) feed a GrayHealthMonitor
        each monitor tick, and ``capacity_factors`` auto-wires to its
        pricing unless the caller installed their own hook. Probation
        relies on the fractional plan keeping SOME load on the engine so
        ratios keep flowing (a folded-empty probationed engine holds its
        state until the packer hands it load again)."""
        from ray_dynamic_batching_tpu.serve.grayhealth import (
            GrayHealthMonitor,
        )

        self.gray = GrayHealthMonitor("scheduler", policy=policy)
        self.gray.audit = self.audit
        self._gray_window_ticks = int(window_ticks)
        self._gray_windows = {}
        for e in self.engines:
            e.track_ratios = True
        if self.capacity_factors is None:
            self.capacity_factors = lambda: {
                e.engine_id: self.gray.capacity_factor(e.engine_id)
                for e in self.engines
            }

    def check_gray_health(self) -> bool:
        """Grade one monitor tick's step ratios and replan when a
        verdict changed the planner's pricing (probation = fractional
        chip, ejection = reclaim). Returns True when a gray replan
        fired. Mirrors SimScheduler.check_gray_health."""
        if self.gray is None:
            return False
        # The SAME window/grade rule the sim twin runs — no drift. No
        # probes map: live has no ground truth to synthesize for an
        # idled probationed engine (see enable_gray_monitoring).
        from ray_dynamic_batching_tpu.serve.grayhealth import (
            ratio_observations,
        )

        drained_by_id = {
            e.engine_id: e.drain_ratios()
            for e in self.engines
            if e.engine_id not in self._dead_engines
            and e.engine_id not in self._gray_ejected
        }
        obs = ratio_observations(
            drained_by_id, self._gray_windows, self._gray_window_ticks
        )
        transitions = self.gray.tick(obs)
        repricing = [t for t in transitions
                     if "probation" in (t["from"], t["to"])
                     or t["to"] == "ejected"]
        if not repricing:
            return False
        for t in repricing:
            if t["to"] == "ejected":
                self._gray_ejected.add(t["replica"])
                for e in self.engines:
                    if e.engine_id == t["replica"]:
                        e.assign(NodePlan())  # idle the reclaimed chip
        self.rebalance(trigger="gray")
        return True

    # --- monitor loop (ref _monitor_request_rates, scheduler.py:763-801) --
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitoring_interval_s):
            try:
                healed = self.check_engine_health()
                if not healed:
                    healed = self.check_gray_health()
                changed = self.rates.changed_models(
                    self.rate_threshold, self.rate_decrease_multiplier,
                    min_span_s=self.rate_min_span_s,
                )
                if changed and not healed:  # heal already replanned
                    logger.info("rate change detected: %s", changed)
                    self.rebalance(trigger="rate_change")
                if self.metrics_path:
                    self.write_metrics()
            except Exception:  # noqa: BLE001
                logger.exception("monitor iteration failed")

    def start_monitoring(self) -> None:
        if self._monitor is not None:
            return
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="rdb-monitor", daemon=True
        )
        self._monitor.start()

    def stop_monitoring(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None

    # --- observability (ref metrics.json writer, scheduler.py:969-983) ----
    def snapshot(self) -> Dict:
        # Snapshot the plan reference under the lock: rebalance rebinds
        # it from the monitor thread while metrics writers read here.
        with self._lock:
            plan = list(self._current_plan)
        return {
            "time": self._clock(),
            "rates_rps": self.rates.rates(),
            "scheduled_rates_rps": self.rates.scheduled_rates(),
            "queues": self.queues.stats(),
            "plan": [n.describe() for n in plan],
            "engines": [e.describe() for e in self.engines],
            "schedule_changes": self.schedule_changes,
            "audit": self.audit.to_dicts(last=20),
        }

    def write_metrics(self) -> None:
        with open(self.metrics_path, "w") as f:
            json.dump(self.snapshot(), f, indent=2)

    def render_status(self) -> str:
        """Terminal SLO status (ref metrics_display.py:42-66) — one table
        renderer for scheduler, state CLI, and dashboard alike."""
        from ray_dynamic_batching_tpu.state import render_queue_table

        return render_queue_table(self.queues.stats(), self.rates.rates())
