"""The decide-replan step — pure, clock-free, shared by live and simulated.

``LiveScheduler.rebalance`` used to fuse three things: reading rates,
DECIDING (bin-pack + minimal-movement matching + audit payload), and
APPLYING (engine.assign, mark_scheduled, audit ring). The decision is a
pure function of (packer, engine residency, sessions, rates) — no
threads, no wall clock, no jax — so it lives here, consumed by BOTH the
threaded live path (`scheduler/control.py`) and the what-if simulator
(`sim/control.py`). The two callers must never fork this logic: a plan
the simulator grades is only trustworthy if it is byte-for-byte the plan
the live control loop would install (the no-drift pin in
``tests/test_sim.py``, same pattern as ``ops/tile_math.py`` sharing the
VMEM math between runtime picker and linter).

Reference lineage: rate-triggered replan + minimal-movement matching,
``293-project/src/scheduler.py:794-929``.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_dynamic_batching_tpu.engine.request import QOS_WEIGHTS
from ray_dynamic_batching_tpu.profiles.table import BatchProfile, mesh_chips
from ray_dynamic_batching_tpu.scheduler.audit import plan_diff
from ray_dynamic_batching_tpu.scheduler.nexus import (
    NodePlan,
    Placement,
    Session,
    SquishyBinPacker,
)
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("replan")

BRUTE_FORCE_LIMIT = 7  # assignment is brute-forced up to this many nodes

# Cross-mesh-shape migration premium (ms-equivalent per weight MB, on
# top of the load+compile the matcher already charges): re-laying a
# resident weight set over a different slice geometry moves every
# parameter byte through an all-gather + re-slice over ICI — roughly a
# read plus a write of the weights — where a same-shape move is a plain
# upload. One constant shared by matcher objective and audit pricing.
RESHARD_MB_FACTOR = 2.0

# Live-stream parcel courier rate (ms per MB of parcel bytes): a page
# fabric migration ships KV page contents + the stream cursor through
# host RAM across replicas (~8 GB/s effective for the gather-serialize-
# scatter round trip -> ~0.125 ms/MB). Priced in the SAME objective as
# resharding so "move the live streams" competes fairly with "move the
# weights" (ISSUE 18); the sim twin and the soak's pause model read this
# exact constant, the usual no-drift discipline.
COURIER_MS_PER_MB = 0.125


@dataclass
class ModelEntry:
    """Registered model contract (ref models_config, scheduler.py:30-35)."""

    name: str
    slo_ms: float
    seq_len: int = 0
    # Preferred serving mesh shape: "1x1" = single chip (the default,
    # and the only shape pre-mesh deployments ever register).
    mesh_shape: str = "1x1"
    # Speculative serving arm (ISSUE 13): "on" makes the packer price
    # this model from its spec profile rows at the PROFILED acceptance
    # rate (Session.spec/spec_acceptance/spec_tokens). Defaults keep
    # every pre-spec registration byte-identical.
    spec: str = "off"
    spec_acceptance: float = 0.0
    spec_tokens: int = 4
    # Chunk-interleaved prefill overhead per decode turn (ISSUE 15;
    # Session.prefill_chunk_ms — 0.0 keeps pre-chunked registrations
    # byte-identical).
    prefill_chunk_ms: float = 0.0


def weighted_attainment(
    class_counters: Dict[str, Dict[str, float]],
    weights: Optional[Dict[str, float]] = None,
) -> float:
    """Class-weighted SLO attainment — the planner's pricing of a miss.

    Plain attainment treats every shed request equally; the QoS contract
    does not: an interactive miss costs :data:`QOS_WEIGHTS` (4x) a
    best-effort one. This is the SHARED pricing function (sim reports,
    the overload-soak grade, live snapshots) so "did degradation stay
    graceful?" is answered by one formula on both sides — same no-drift
    discipline as ``decide_replan`` itself. Shed load (stale + dropped)
    counts as missed, exactly like ``sim/report.slo_attainment``.

    ``class_counters`` is per class ``{completed, violations, stale,
    dropped}`` (the queue's ``class_stats()`` shape). 1.0 when idle."""
    weights = weights if weights is not None else QOS_WEIGHTS
    w_accounted = 0.0
    w_missed = 0.0
    for cls, c in class_counters.items():
        w = weights.get(cls, 1.0)
        accounted = (c.get("completed", 0.0) + c.get("stale", 0.0)
                     + c.get("dropped", 0.0))
        missed = (c.get("violations", 0.0) + c.get("stale", 0.0)
                  + c.get("dropped", 0.0))
        w_accounted += w * accounted
        w_missed += w * missed
    return 1.0 - w_missed / w_accounted if w_accounted else 1.0


def sessions_for(
    models: Dict[str, ModelEntry], rates: Dict[str, float]
) -> List[Session]:
    """Sessions at the observed rates — the packer's input."""
    return [
        Session(
            model=e.name,
            slo_ms=e.slo_ms,
            rate_rps=rates.get(e.name, 0.0),
            seq_len=e.seq_len,
            mesh_shape=e.mesh_shape,
            spec=e.spec,
            spec_acceptance=e.spec_acceptance,
            spec_tokens=e.spec_tokens,
            prefill_chunk_ms=e.prefill_chunk_ms,
        )
        for e in models.values()
    ]


def degrade_sessions(
    sessions: List[Session],
    engine_widths: Optional[Sequence[int]],
    profiles: Dict[str, BatchProfile],
) -> Tuple[List[Session], Dict[str, Dict[str, str]]]:
    """Clamp each session's mesh shape to the SURVIVING slice geometry.

    A TP=4 model whose 4-chip slice just died must not demand a slice
    that no longer exists — it degrades to the largest profiled shape a
    surviving slice can carry (TP=4 -> the TP=2 row when only a
    half-slice remains), and upgrades back the moment a wide slice
    reappears (the same clamp, run at every decision, is the heal).
    Returns (sessions, {model: {"from": .., "to": ..}}) — the second
    half feeds the replan audit so a degraded placement is never
    silent. Pure: same inputs, same outputs, live and sim share it."""
    widths = {int(w) for w in (engine_widths if engine_widths else [1])}
    out: List[Session] = []
    degraded: Dict[str, Dict[str, str]] = {}
    for s in sessions:
        prof = profiles.get(s.model)
        shapes = prof.meshes() if prof is not None else ["1x1"]
        if s.chips in widths and s.mesh_shape in shapes:
            out.append(s)
            continue
        fitting = [sh for sh in shapes if mesh_chips(sh) in widths]
        best = None
        for sh in fitting:  # meshes() is ascending in chips
            if mesh_chips(sh) <= s.chips:
                best = sh
        if best is None and fitting:
            best = fitting[0]  # nothing smaller profiled: smallest fit
        if best is None or best == s.mesh_shape:
            out.append(s)  # nowhere to degrade to — starve loudly below
            continue
        degraded[s.model] = {"from": s.mesh_shape, "to": best}
        out.append(replace(s, mesh_shape=best))
    return out, degraded


def reshard_cost(
    model: str,
    from_mesh: str,
    to_mesh: str,
    profiles: Dict[str, BatchProfile],
) -> float:
    """Premium for moving a resident model BETWEEN mesh shapes: every
    weight byte transits an all-gather + re-slice over ICI on top of the
    plain upload the matcher already prices. 0 for a same-shape move.
    Priced at the DESTINATION shape's per-chip shard (the bytes each
    chip of the new slice must end up holding) — on mixed-mesh tables
    the unrestricted weights min is the widest mesh's shard, which
    would underprice every narrowing reshard."""
    if from_mesh == to_mesh:
        return 0.0
    prof = profiles.get(model)
    weight_mb = (prof.weights_hbm_bytes(to_mesh) / 1e6
                 if prof is not None else 1.0)
    return RESHARD_MB_FACTOR * weight_mb


def transfer_cost(
    engine_models: frozenset,
    plan: NodePlan,
    profiles: Dict[str, BatchProfile],
    resident_meshes: Optional[Dict[str, str]] = None,
) -> float:
    """Cost of pointing an engine at ``plan``: for every model the engine
    doesn't already host, charge weight bytes (upload) + compile time —
    plus the reshard premium when the model is currently resident
    SOMEWHERE in the domain at a different mesh shape than the plan's
    (``resident_meshes``: model -> hosted shape; None = classic
    single-chip pricing, byte-identical to the pre-mesh matcher)."""
    cost = 0.0
    for p in plan.placements:
        name = p.session.model
        if name in engine_models:
            continue
        prof = profiles.get(name)
        if prof is None:
            cost += 1.0
            continue
        # Keyed by the session's SPEC arm too (ISSUE 13): a spec
        # session's resident program set (draft + verify) is described
        # by its spec rows — compile_ms/hbm differ from the plain arm,
        # and on a spec-only table the default "off" lookup would find
        # nothing and silently price the 1000 ms compile guess.
        row = prof.row_for(
            p.batch_size, p.session.seq_len, plan.mesh_shape,
            p.session.spec,
        ) or prof.bucket_for(p.batch_size, p.session.seq_len,
                             plan.mesh_shape, p.session.spec)
        compile_ms = row.compile_ms if row else 1000.0
        # Upload priced at the PLAN's shape AND the session's spec arm:
        # each chip of the slice uploads its own weight shard, and a
        # spec session's set includes the draft model's weights (the
        # plain rows' min would shave them off). Single-shape/-arm
        # tables are unchanged.
        weight_mb = prof.weights_hbm_bytes(
            plan.mesh_shape, p.session.spec
        ) / 1e6
        cost += compile_ms + weight_mb  # ms-equivalent weighting
        if resident_meshes is not None and name in resident_meshes:
            cost += reshard_cost(
                name, resident_meshes[name], plan.mesh_shape, profiles
            )
    return cost


def fold_node_plans(target: NodePlan, extra: NodePlan) -> NodePlan:
    """Merge two node plans onto one chip: duty cycles add, occupancies
    rescale (``occ * old_duty / new_duty``) so every placement keeps its
    absolute slice milliseconds — degraded latency, never starvation.
    The fold keeps the TARGET's mesh shape (callers only fold
    same-shape plans — a program compiled for one slice geometry cannot
    time-slice on another)."""
    new_duty = target.duty_cycle_ms + extra.duty_cycle_ms
    if new_duty <= 0:
        return NodePlan(
            placements=list(target.placements) + list(extra.placements),
            duty_cycle_ms=new_duty,
            mesh_shape=target.mesh_shape,
        )
    rescaled = []
    for node in (target, extra):
        scale = node.duty_cycle_ms / new_duty
        rescaled.extend(
            Placement(p.session, p.batch_size, p.latency_ms,
                      p.occupancy * scale, p.hbm_bytes)
            for p in node.placements
        )
    return NodePlan(placements=rescaled, duty_cycle_ms=new_duty,
                    mesh_shape=target.mesh_shape)


def merge_overflow_nodes(
    plans: List[NodePlan], n_engines: int
) -> List[NodePlan]:
    """Fold a plan that needs more chips than exist onto the chips that
    do exist (degraded latency, never starvation).

    When the packer wants ``len(plans) > n_engines`` — typical right
    after an engine death shrinks the cluster — simply truncating would
    SILENTLY drop every model exclusive to the overflow nodes: their
    shared queues starve with no shed accounting (requests neither
    complete nor reject). Instead each overflow node is merged into the
    least-occupied retained node: duty cycles add, and occupancies are
    rescaled (``occ * old_duty / new_duty``) so every placement keeps
    its absolute slice milliseconds — each model still runs every
    ``new_duty`` ms, trading latency for coverage, which the SLO
    accounting then prices honestly as violations/sheds rather than
    hangs."""
    if n_engines <= 0 or len(plans) <= n_engines:
        return list(plans)
    merged = [
        NodePlan(placements=list(n.placements),
                 duty_cycle_ms=n.duty_cycle_ms,
                 mesh_shape=n.mesh_shape)
        for n in plans[:n_engines]
    ]
    for extra in plans[n_engines:]:
        host = min(range(len(merged)), key=lambda i: merged[i].occupancy)
        merged[host] = fold_node_plans(merged[host], extra)
    return merged


def fit_plans_to_geometry(
    plans: List[NodePlan], engine_widths: Sequence[int]
) -> List[NodePlan]:
    """Shrink a plan list onto a WIDTH-TYPED engine set: plans group by
    slice width, each group folds down (``merge_overflow_nodes``) to the
    number of engines of that width, and a group whose width has no
    engine at all is dropped with a loud capacity warning (its models
    re-enter planning next tick — typically degraded to a surviving
    shape by ``degrade_sessions`` — instead of silently starving behind
    an unassignable plan)."""
    from collections import Counter

    cap = Counter(int(w) for w in engine_widths)
    by_width: Dict[int, List[NodePlan]] = {}
    for p in plans:
        by_width.setdefault(p.chips, []).append(p)
    out: List[NodePlan] = []
    for width in sorted(by_width):
        group = by_width[width]
        have = cap.get(width, 0)
        if have == 0:
            logger.warning(
                "no %d-chip slice exists for %d node plan(s) (%s); "
                "dropping — geometry cannot carry this shape (capacity!)",
                width, len(group),
                sorted({m for n in group for m in n.models}),
            )
            continue
        if len(group) > have:
            logger.warning(
                "plan needs %d %d-chip slices but only %d exist; merging "
                "overflow nodes (degraded latency; capacity!)",
                len(group), width, have,
            )
            group = merge_overflow_nodes(group, have)
        out.extend(group)
    return out


def derate_for_capacity(
    assignment: List[Optional[NodePlan]],
    capacity_factors: Sequence[float],
    engine_widths: Optional[Sequence[int]] = None,
) -> Dict[int, Dict[str, int]]:
    """Price degraded engines as FRACTIONAL capacity (gray-failure
    probation, ISSUE 9) instead of alive/dead. Mutates ``assignment``
    in place; returns per-engine notes for the audit payload.

    An engine with ``factor < 1`` may only carry a plan whose occupancy
    fits the factor. First choice: SWAP its plan with the lightest
    fitting plan held by a full-capacity engine — the probationed chip
    keeps serving (its traffic doubles as the probe stream that makes a
    heal observable) while the heavy work moves to healthy hardware.
    Fallback: FOLD the whole plan onto the least-occupied full-capacity
    engine (degraded latency there, honest shed accounting — never a
    starved queue). With no full-capacity engine at all, the plan stays:
    slow beats starved. Width-typed engine sets (mesh slices) swap and
    fold only between SAME-WIDTH engines — a slice program cannot land
    on a chip set of a different width."""
    moved: Dict[int, Dict[str, int]] = {}
    widths = ([int(w) for w in engine_widths] if engine_widths is not None
              else [1] * len(capacity_factors))
    full = [j for j, f in enumerate(capacity_factors) if f >= 1.0 - 1e-9]
    for e, factor in enumerate(capacity_factors):
        plan = assignment[e]
        if (factor >= 1.0 - 1e-9 or plan is None
                or plan.occupancy <= factor + 1e-9):
            continue
        swaps = [
            j for j in full
            if widths[j] == widths[e]
            and assignment[j] is not None
            and assignment[j].occupancy <= factor + 1e-9
            and assignment[j].occupancy < plan.occupancy
        ]
        if swaps:
            j = min(swaps, key=lambda j: (assignment[j].occupancy, j))
            assignment[e], assignment[j] = assignment[j], assignment[e]
            moved[e] = {"swapped_with": j}
            continue
        hosts = [j for j in full if j != e and widths[j] == widths[e]]
        if not hosts:
            continue
        j = min(hosts, key=lambda j: (
            assignment[j].occupancy if assignment[j] is not None else 0.0,
            j,
        ))
        assignment[j] = (fold_node_plans(assignment[j], plan)
                         if assignment[j] is not None else plan)
        assignment[e] = None
        moved[e] = {"folded_into": j}
    return moved


def match_plans_to_engines(
    engine_models: List[frozenset],
    plans: List[NodePlan],
    profiles: Dict[str, BatchProfile],
    engine_widths: Optional[Sequence[int]] = None,
    resident_meshes: Optional[Dict[str, str]] = None,
) -> List[Optional[NodePlan]]:
    """Assign new node plans to engines minimizing total transfer cost.

    Brute-force over permutations for small counts (the reference's approach,
    scheduler.py:857-891), greedy best-match beyond BRUTE_FORCE_LIMIT.
    Returns, per engine, its new plan (None = engine idles).

    ``engine_widths`` types each engine as a chip SET (a mesh slice):
    a node plan may only land on an engine of exactly its width — a
    4-chip TP program cannot run on a single chip, and a single-chip
    duty cycle does not time-slice a gang-scheduled slice. None (the
    classic callers) = every engine is one chip, byte-identical
    behavior. ``resident_meshes`` threads the reshard premium into the
    matcher's own objective (see :func:`transfer_cost`)."""
    n_engines = len(engine_models)
    if engine_widths is None:
        padded: List[Optional[NodePlan]] = list(plans) + [None] * max(
            0, n_engines - len(plans)
        )
        if len(plans) > n_engines:
            logger.warning(
                "plan needs %d chips but only %d engines; merging overflow "
                "nodes (degraded latency; capacity!)",
                len(plans), n_engines,
            )
            padded = merge_overflow_nodes(plans, n_engines)
        widths = [1] * n_engines
    else:
        widths = [int(w) for w in engine_widths]
        fitted = fit_plans_to_geometry(plans, widths)
        padded = list(fitted) + [None] * (n_engines - len(fitted))

    def compatible(plan: Optional[NodePlan], e: int) -> bool:
        return plan is None or plan.chips == widths[e]

    if n_engines <= BRUTE_FORCE_LIMIT:
        best: Optional[Tuple[float, Tuple[int, ...]]] = None
        for perm in itertools.permutations(range(n_engines)):
            if not all(
                compatible(padded[i], e) for i, e in enumerate(perm)
            ):
                continue
            cost = sum(
                transfer_cost(engine_models[e], padded[i], profiles,
                              resident_meshes)
                for i, e in enumerate(perm)
                if padded[i] is not None
            )
            if best is None or cost < best[0]:
                best = (cost, perm)
        assignment: List[Optional[NodePlan]] = [None] * n_engines
        if best is None:  # fit_plans_to_geometry makes this unreachable
            logger.warning("no width-compatible assignment exists")
            return assignment
        for i, e in enumerate(best[1]):
            assignment[e] = padded[i]
        return assignment

    # Greedy: most expensive-to-move plans pick their cheapest engine first.
    order = sorted(
        [i for i, p in enumerate(padded) if p is not None],
        key=lambda i: -max(
            transfer_cost(m, padded[i], profiles, resident_meshes)
            for m in engine_models
        ),
    )
    free = set(range(n_engines))
    assignment = [None] * n_engines
    for i in order:
        fits = [e for e in free if compatible(padded[i], e)]
        if not fits:  # fit_plans_to_geometry makes this unreachable
            logger.warning(
                "no free %d-chip engine for plan %s",
                padded[i].chips, padded[i].describe(),
            )
            continue
        # Tie-break toward engines hosting fewer models so a zero-savings
        # plan lands on an empty engine instead of displacing a warm one.
        e = min(
            fits,
            key=lambda e: (
                transfer_cost(engine_models[e], padded[i], profiles,
                              resident_meshes),
                len(engine_models[e]),
                e,
            ),
        )
        assignment[e] = padded[i]
        free.remove(e)
    return assignment


@dataclass
class ReplanDecision:
    """Everything one replan decided, before anything is applied."""

    plan: List[NodePlan]
    assignment: List[Optional[NodePlan]]   # per engine; None = idle
    old_models: List[List[str]] = field(default_factory=list)
    new_models: List[List[str]] = field(default_factory=list)
    migration_cost: float = 0.0
    rates: Dict[str, float] = field(default_factory=dict)
    # Gray-failure pricing (ISSUE 9): the per-engine capacity factors the
    # decision was made under (None = every engine priced as a full chip)
    # and what the derate pass moved because of them.
    capacity_factors: Optional[List[float]] = None
    derated: Dict[int, Dict[str, int]] = field(default_factory=dict)
    # Mesh geometry (ROADMAP item 2): the slice widths the decision was
    # made over, and any sessions clamped to a surviving shape
    # (``degrade_sessions``). Empty/None on classic single-chip domains
    # so pre-mesh audit payloads stay byte-identical.
    engine_widths: Optional[List[int]] = None
    mesh_degraded: Dict[str, Dict[str, str]] = field(default_factory=dict)
    # Page-fabric courier share of migration_cost (ISSUE 18): what the
    # live-stream parcels leaving reassigned engines cost, already summed
    # into migration_cost. 0.0 (and elided from audits) when the caller
    # passed no parcel sizes — pre-fabric decisions stay byte-identical.
    live_migration_cost: float = 0.0

    def audit_fields(self) -> Dict[str, Any]:
        """The structured-audit payload (``scheduler/audit.py``), built
        fresh per call so rings never alias a shared dict."""
        observed: Dict[str, Any] = {
            "rates_rps": {k: round(v, 2) for k, v in self.rates.items()},
        }
        if self.capacity_factors is not None and any(
            f < 1.0 for f in self.capacity_factors
        ):
            observed["capacity_factors"] = [
                round(f, 3) for f in self.capacity_factors
            ]
            observed["derated"] = {
                str(k): v for k, v in sorted(self.derated.items())
            }
        if self.engine_widths is not None and any(
            w != 1 for w in self.engine_widths
        ):
            observed["engine_widths"] = list(self.engine_widths)
        if self.mesh_degraded:
            observed["mesh_degraded"] = {
                k: dict(v) for k, v in sorted(self.mesh_degraded.items())
            }
        placements = []
        for n in self.plan:
            for p in n.placements:
                entry = {"model": p.session.model, "batch": p.batch_size,
                         "latency_ms": round(p.latency_ms, 2),
                         "occupancy": round(p.occupancy, 3)}
                if n.mesh_shape != "1x1":
                    entry["mesh"] = n.mesh_shape
                placements.append(entry)
        if self.live_migration_cost > 0:
            observed["live_migration_cost"] = round(
                self.live_migration_cost, 1
            )
        return {
            "observed": observed,
            "inputs": {
                # The profile rows the packer committed to: per
                # placement, the (batch, latency) row that sized it.
                "placements": placements,
            },
            "before": [", ".join(m) for m in self.old_models],
            "after": [", ".join(m) for m in self.new_models],
            "diff": plan_diff(self.old_models, self.new_models),
            "migration_cost": round(self.migration_cost, 1),
        }


def decide_replan(
    packer: SquishyBinPacker,
    engine_models: Sequence[frozenset],
    sessions: List[Session],
    rates: Dict[str, float],
    capacity_factors: Optional[Sequence[float]] = None,
    engine_widths: Optional[Sequence[int]] = None,
    engine_meshes: Optional[Sequence[str]] = None,
    live_parcel_bytes: Optional[Sequence[float]] = None,
) -> ReplanDecision:
    """One replan, decided but not applied: bin-pack the sessions, match
    the resulting node plans onto the engines with minimal movement, and
    price the migration (the matcher's own objective — compile_ms +
    weight-MB for models not already resident, plus the reshard premium
    for cross-mesh-shape moves).

    ``capacity_factors`` (aligned with ``engine_models``; default all
    1.0) prices gray-degraded engines as FRACTIONAL chips: after
    matching, plans that overfill a derated engine are swapped with or
    folded onto full-capacity peers (:func:`derate_for_capacity`) — the
    probation story between alive and dead.

    ``engine_widths`` / ``engine_meshes`` (aligned with
    ``engine_models``) make the schedulable unit a chip SET: sessions
    degrade to the surviving slice geometry (:func:`degrade_sessions` —
    a TP=4 model falls back to its TP=2 row when only a half-slice
    remains), plans land only on width-matching engines, and moving a
    resident model between shapes is priced as a weight-reshard. None =
    the classic one-chip-per-engine domain, byte-identical decisions.

    ``live_parcel_bytes`` (aligned with ``engine_models``; ISSUE 18)
    gives each engine's live-stream KV parcel size: engines whose model
    set CHANGES under the new assignment must also courier those streams
    to their new homes, priced at :data:`COURIER_MS_PER_MB` in the same
    objective — a replan that would bounce many hot streams loses to one
    that leaves them put. None keeps pre-fabric decisions byte-identical."""
    engine_models = [frozenset(m) for m in engine_models]
    widths: Optional[List[int]] = None
    mesh_degraded: Dict[str, Dict[str, str]] = {}
    resident_meshes: Optional[Dict[str, str]] = None
    if engine_widths is not None:
        widths = [int(w) for w in engine_widths]
        if len(widths) != len(engine_models):
            raise ValueError(
                f"engine_widths has {len(widths)} entries for "
                f"{len(engine_models)} engines"
            )
        sessions, mesh_degraded = degrade_sessions(
            sessions, widths, packer.profiles
        )
    if engine_meshes is not None:
        if len(engine_meshes) != len(engine_models):
            raise ValueError(
                f"engine_meshes has {len(engine_meshes)} entries for "
                f"{len(engine_models)} engines"
            )
        resident_meshes = {}
        for mesh, models in zip(engine_meshes, engine_models):
            for m in models:
                resident_meshes.setdefault(m, str(mesh))
    plan = packer.plan(sessions)
    assignment = match_plans_to_engines(
        engine_models, plan, packer.profiles,
        engine_widths=widths, resident_meshes=resident_meshes,
    )
    derated: Dict[int, Dict[str, int]] = {}
    factors: Optional[List[float]] = None
    if capacity_factors is not None:
        factors = [float(f) for f in capacity_factors]
        if len(factors) != len(engine_models):
            raise ValueError(
                f"capacity_factors has {len(factors)} entries for "
                f"{len(engine_models)} engines"
            )
        derated = derate_for_capacity(assignment, factors,
                                      engine_widths=widths)
    migration_cost = sum(
        transfer_cost(engine_models[e], n, packer.profiles,
                      resident_meshes)
        for e, n in enumerate(assignment)
        if n is not None
    )
    live_cost = 0.0
    if live_parcel_bytes is not None:
        parcels = [float(b) for b in live_parcel_bytes]
        if len(parcels) != len(engine_models):
            raise ValueError(
                f"live_parcel_bytes has {len(parcels)} entries for "
                f"{len(engine_models)} engines"
            )
        for e, n in enumerate(assignment):
            new = frozenset(n.models) if n is not None else frozenset()
            if new != engine_models[e] and parcels[e] > 0:
                live_cost += parcels[e] / 1e6 * COURIER_MS_PER_MB
        migration_cost += live_cost
    return ReplanDecision(
        plan=plan,
        assignment=assignment,
        old_models=[sorted(m) for m in engine_models],
        new_models=[
            sorted(n.models) if n is not None else [] for n in assignment
        ],
        migration_cost=migration_cost,
        rates=dict(rates),
        capacity_factors=factors,
        derated=derated,
        engine_widths=widths,
        mesh_degraded=mesh_degraded,
        live_migration_cost=live_cost,
    )
