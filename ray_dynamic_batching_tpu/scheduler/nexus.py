"""Squishy bin packing — SLO-aware multi-model chip allocation (Nexus §6.1).

Re-creates the algorithm of the reference's ``293-project/src/nexus.py``
(``scheduleSaturate`` :145, ``scheduleResidue`` :241, ``mergeNodes`` :203,
entry ``squishyBinPacking`` :129) with a TPU cost model:

- **HBM budget replaces gpu_mem** (ref nexus.py:156-165): a placement's
  footprint comes from the profile row's measured program footprint
  (weights + activations), and co-located models must *sum* within the chip's
  planning budget — weights stay resident in HBM across the duty cycle
  (there is no ``torch.cuda.empty_cache()`` hot path on TPU).
- **Batches are buckets**: candidate batch sizes are the profiled XLA
  buckets; merges re-derive batch = ceil(duty*rate/1000) (ref nexus.py:208)
  then round UP to a bucket, so a merged schedule never runs an uncompiled
  shape.
- **No preemptive time-slicing** (SURVEY.md §7(c)): occupancy is computed
  from worst-case step latency (mean + 2*std) because a long compiled step
  cannot be preempted mid-flight to honor a co-tenant's slice.
- The **SLO/2 rule** (ref nexus.py:154): a batch is admissible iff
  2 * worst_latency(batch) <= slo — half the budget for queueing, half for
  compute.

Vocabulary mapping (reference → here): session → :class:`Session`,
node → :class:`NodePlan`, (session, occupancy) pairs → :class:`Placement`.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.profiles.table import (
    BatchProfile,
    ProfileRow,
    expected_tokens_per_round,
    mesh_chips,
)
from ray_dynamic_batching_tpu.utils.config import get_config


@dataclass(frozen=True)
class Session:
    """A model's serving contract at the current request rate
    (ref: session, nexus.py:17)."""

    model: str
    slo_ms: float
    rate_rps: float
    seq_len: int = 0  # shape bucket for LLM prefill; 0 = fixed-shape
    # Mesh shape this model serves at (ROADMAP item 2): the packer prices
    # it from the profile rows measured at this shape and emits node
    # plans over mesh_chips(mesh_shape)-wide chip SETS. "1x1" = the
    # classic single-chip duty-cycle placement.
    mesh_shape: str = "1x1"
    # Speculative serving arm (ISSUE 13): "on" prices this model from
    # its spec profile rows — a row's per-ROUND latency divided by
    # expected_tokens_per_round(spec_acceptance, spec_tokens), the
    # planner's honest belief about what the PROFILED acceptance rate
    # buys. "off" (default) is byte-identical to the pre-spec packer.
    spec: str = "off"
    spec_acceptance: float = 0.0
    spec_tokens: int = 4
    # Chunk-interleaved prefill overhead (ISSUE 15): the expected
    # per-turn milliseconds of budgeted chunk work riding each decode
    # turn of this model (prefill_token_budget's worth of chunk program
    # between turns, amortized over the turns a cycle runs). 0.0 — the
    # default every pre-chunked registration keeps — is byte-identical
    # to the pre-interleave packer.
    prefill_chunk_ms: float = 0.0

    @property
    def chips(self) -> int:
        return mesh_chips(self.mesh_shape)


@dataclass
class Placement:
    """One session's slice of a chip (ref: node.sessions + occupancy lists)."""

    session: Session
    batch_size: int
    latency_ms: float       # worst-case step latency at this batch
    occupancy: float        # latency / duty_cycle
    hbm_bytes: int


@dataclass
class NodePlan:
    """One schedulable unit's duty-cycle schedule (ref: node, nexus.py:75).

    A unit is one chip (``mesh_shape == "1x1"``) or one mesh SLICE: a
    gang of ``chips`` chips running the co-located models' programs
    GSPMD-partitioned over the slice. ``hbm_bytes`` stays per-chip
    (mesh profile rows record per-chip footprints), so the chip budget
    check is shape-invariant."""

    placements: List[Placement] = field(default_factory=list)
    duty_cycle_ms: float = 0.0
    mesh_shape: str = "1x1"

    @property
    def chips(self) -> int:
        return mesh_chips(self.mesh_shape)

    @property
    def occupancy(self) -> float:
        return sum(p.occupancy for p in self.placements)

    @property
    def hbm_bytes(self) -> int:
        return sum(p.hbm_bytes for p in self.placements)

    @property
    def models(self) -> List[str]:
        return [p.session.model for p in self.placements]

    def describe(self) -> str:
        parts = ", ".join(
            f"{p.session.model}(b={p.batch_size}, occ={p.occupancy:.2f})"
            for p in self.placements
        )
        mesh = "" if self.mesh_shape == "1x1" else f"mesh={self.mesh_shape}, "
        return f"NodePlan(duty={self.duty_cycle_ms:.1f}ms, {mesh}[{parts}])"


def worst_latency_ms(row: ProfileRow) -> float:
    """Occupancy math uses worst-case step latency (no preemption on TPU)."""
    return row.latency_ms + 2.0 * row.latency_std_ms


class SquishyBinPacker:
    """The planner. One instance per scheduling domain (a set of identical
    chips); profiles keyed by model name."""

    def __init__(
        self,
        profiles: Dict[str, BatchProfile],
        hbm_budget_bytes: Optional[int] = None,
    ):
        cfg = get_config()
        self.profiles = profiles
        self.hbm_budget = int(
            (hbm_budget_bytes or cfg.hbm_budget_bytes) * cfg.hbm_plan_fraction
        )
        self.slo_safety = cfg.slo_safety_factor
        self.compute_fraction = cfg.slo_compute_fraction
        # Turn-cost pricing (ISSUE 7): "batch" charges every duty-cycle
        # slice the FULL bucket latency regardless of expected fill —
        # correct for slab/shape-bucketed decode, where a 3-request turn
        # in a 16-slot program costs the whole step. "slot" prices the
        # slice at its expected fill (continuous batching on the paged
        # pool: turn cost ~ floor + (1 - floor) * fill), so residue
        # merges pack partially-full decode turns instead of whole-batch
        # steps. SLO admission stays worst-case (a full turn can still
        # happen); only CAPACITY pricing changes. Default "batch" — the
        # sim pins it per scenario; live control opts in with the paged
        # engines.
        self.occupancy_pricing = "batch"
        self.occupancy_floor = 0.35

    def _session_wl(self, session: Session, row: ProfileRow) -> float:
        """Worst-case EFFECTIVE step latency of ``session`` at ``row``:
        a spec row's latency is one verify ROUND, so a spec session
        divides it by the expected tokens that round emits at the
        PROFILED acceptance rate (``expected_tokens_per_round`` — one
        shared formula with the sim engine's execution model). Non-spec
        sessions (and spec sessions whose table lacks spec rows — the
        ``_seq_rows`` fallback hands back plain rows, ``row.spec ==
        "off"``) price exactly as before, bit for bit."""
        wl = worst_latency_ms(row)
        if session.spec == "on" and row.spec == "on":
            wl = wl / expected_tokens_per_round(
                session.spec_acceptance, session.spec_tokens
            )
        if session.prefill_chunk_ms > 0.0:
            # Chunk-interleaved turns (ISSUE 15): each decode turn of a
            # chunked-admission engine may carry one budget's worth of
            # chunk program between it and the next — the stall bound
            # the engine enforces is exactly the cost the planner must
            # price, or co-located tenants get admitted into turns that
            # are secretly longer than their profile row.
            wl = wl + session.prefill_chunk_ms
        return wl

    def _turn_cost_ms(self, wl: float, fill: float) -> float:
        """Expected cost of one duty-cycle turn at ``fill`` (0..1] of the
        bucket: the fill-invariant floor is the weight stream every
        decode turn pays, the proportional part the per-slot KV scan."""
        if self.occupancy_pricing != "slot":
            return wl
        fill = min(max(fill, 0.0), 1.0)
        return wl * (self.occupancy_floor
                     + (1.0 - self.occupancy_floor) * fill)

    # --- admissible batch selection (ref nexus.py:145-165) ----------------
    def _effective_slo(self, session: Session) -> float:
        return session.slo_ms / self.slo_safety

    def saturate_row(self, session: Session) -> Optional[ProfileRow]:
        """Largest profiled bucket with worst_latency <= compute share of SLO
        and footprint within the chip budget. Rows come from the
        session's MESH SHAPE (per-slice latency, per-chip footprint), so
        a TP placement is priced from its own measured tables."""
        prof = self.profiles[session.model]
        budget_ms = self._effective_slo(session) * self.compute_fraction
        best = None
        for row in prof._seq_rows(session.seq_len, session.mesh_shape,
                                  session.spec):
            if (
                self._session_wl(session, row) <= budget_ms
                and row.hbm_bytes <= self.hbm_budget
            ):
                best = row
        return best

    # --- phase 1: saturated nodes (ref scheduleSaturate, nexus.py:145) ----
    def schedule_saturate(
        self, sessions: List[Session]
    ) -> Tuple[List[NodePlan], List[Session]]:
        """Split each session's rate R = n * maxThroughput + r
        (ref nexus.py:181-190); emit n fully-saturated single-model nodes and
        return the residue sessions for phase 2."""
        nodes: List[NodePlan] = []
        residues: List[Session] = []
        for session in sessions:
            row = self.saturate_row(session)
            if row is None:
                # No bucket fits the SLO: serve at the smallest bucket anyway
                # (degraded), one request-rate's worth of nodes.
                prof = self.profiles[session.model]
                rows = prof._seq_rows(session.seq_len, session.mesh_shape,
                                      session.spec)
                if not rows:
                    raise KeyError(
                        f"no profile rows for {session.model} at mesh "
                        f"{session.mesh_shape}"
                    )
                row = rows[0]
            wl = self._session_wl(session, row)
            max_throughput = row.batch_size / (wl / 1000.0)
            n_full = int(session.rate_rps // max_throughput)
            residue_rate = session.rate_rps - n_full * max_throughput
            for _ in range(n_full):
                nodes.append(
                    NodePlan(
                        placements=[
                            Placement(
                                session=session,
                                batch_size=row.batch_size,
                                latency_ms=wl,
                                occupancy=1.0,
                                hbm_bytes=row.hbm_bytes,
                            )
                        ],
                        duty_cycle_ms=wl,
                        mesh_shape=session.mesh_shape,
                    )
                )
            if residue_rate > 1e-9:
                residues.append(replace(session, rate_rps=residue_rate))
        return nodes, residues

    # --- phase 2: residue nodes (ref scheduleResidue, nexus.py:241) -------
    def residue_node(self, session: Session) -> Optional[NodePlan]:
        """Single-session node at its residual rate: pick the largest bucket
        whose *end-to-end* time — batch fill at the arrival rate plus compute —
        fits the SLO (ref nexus.py:246-257: bisect over latency + batch/rate);
        duty = batch/rate*1000, occupancy = latency/duty (ref nexus.py:263-268).
        """
        prof = self.profiles[session.model]
        rows = prof._seq_rows(session.seq_len, session.mesh_shape,
                              session.spec)
        rows = [r for r in rows if r.hbm_bytes <= self.hbm_budget]
        if not rows:
            return None
        slo = self._effective_slo(session)
        rate = max(session.rate_rps, 1e-9)
        chosen = rows[0]
        feasible = False
        for cand in rows:
            fill_ms = cand.batch_size / rate * 1000.0
            if self._session_wl(session, cand) + fill_ms <= slo:
                chosen = cand
                feasible = True
        wl = self._session_wl(session, chosen)
        duty = max(chosen.batch_size / rate * 1000.0, wl)
        if not feasible:
            # Even the smallest bucket cannot FILL within the SLO at this
            # arrival rate (the ref's duty = batch/rate, nexus.py:263-268,
            # would stretch the cycle past the deadline and every queued
            # request would wait it out). Serve under-filled batches
            # instead: bound the cycle by the SLO headroom so wait-one-
            # cycle + compute still fits. Costs occupancy, holds the SLO.
            duty = max(min(duty, slo - wl), wl)
        # Expected fill of one cycle's turn at this duty: under-filled
        # cycles (the not-feasible branch above) cost less than a full
        # step under slot pricing.
        fill = duty * rate / 1000.0 / chosen.batch_size
        return NodePlan(
            placements=[
                Placement(
                    session=session,
                    batch_size=chosen.batch_size,
                    latency_ms=wl,
                    occupancy=min(self._turn_cost_ms(wl, fill) / duty, 1.0),
                    hbm_bytes=chosen.hbm_bytes,
                )
            ],
            duty_cycle_ms=duty,
            mesh_shape=session.mesh_shape,
        )

    # --- merge (ref mergeNodes, nexus.py:202-228) --------------------------
    def try_merge(self, a: NodePlan, b: NodePlan) -> Optional[NodePlan]:
        """Merge two nodes onto one chip at duty = min(duties) (the reference
        keeps the lower-duty node's cycle so no session ever waits longer,
        nexus.py:203-207): every session's batch is re-derived as
        ceil(duty * rate / 1000) rounded UP to a profiled bucket
        (ref nexus.py:211); feasible iff total occupancy <= 1
        (ref nexus.py:218), summed HBM fits (ref nexus.py:222-226, gpu_mem →
        HBM budget), and — TPU addition — each re-derived bucket still meets
        its session's SLO end-to-end (bucket rounding can pick a bigger
        program than the exact batch the reference would run). Mesh
        addition: co-location is WITHIN a slice shape only — a 1x4
        slice's duty cycle can host another 1x4 program, but folding a
        single-chip program onto a slice (or vice versa) would change
        the chip set under a compiled program."""
        if a.mesh_shape != b.mesh_shape:
            return None
        duty = min(a.duty_cycle_ms, b.duty_cycle_ms)
        placements: List[Placement] = []
        hbm_total = 0
        occ_total = 0.0
        for p in a.placements + b.placements:
            s = p.session
            need = max(math.ceil(duty * s.rate_rps / 1000.0), 1)
            prof = self.profiles[s.model]
            row = prof.bucket_for(need, s.seq_len, s.mesh_shape, s.spec)
            if row is None:
                return None  # rate too high for any compiled bucket at this duty
            wl = self._session_wl(s, row)
            if wl + duty > self._effective_slo(s):
                return None  # wait-one-cycle + compute would blow the SLO
            # Capacity pricing at the EXPECTED turn fill (need requests
            # arrive per cycle; the bucket rounded up past it): slab
            # pricing charges the full step, slot pricing the fill-scaled
            # turn — the packing lever continuous batching unlocks.
            occ = self._turn_cost_ms(wl, need / row.batch_size) / duty
            occ_total += occ
            hbm_total += row.hbm_bytes
            if occ_total > 1.0 + 1e-9 or hbm_total > self.hbm_budget:
                return None
            placements.append(
                Placement(
                    session=s,
                    batch_size=row.batch_size,
                    latency_ms=wl,
                    occupancy=occ,
                    hbm_bytes=row.hbm_bytes,
                )
            )
        return NodePlan(placements=placements, duty_cycle_ms=duty,
                        mesh_shape=a.mesh_shape)

    def merge_residues(self, nodes: List[NodePlan]) -> List[NodePlan]:
        """Best-fit decreasing: walk residue nodes by descending occupancy and
        merge each into whichever existing node yields the highest resulting
        occupancy (ref nexus.py:271-293)."""
        merged: List[NodePlan] = []
        for residual in sorted(nodes, key=lambda n: -n.occupancy):
            best: Optional[NodePlan] = None
            best_idx = -1
            for i, existing in enumerate(merged):
                candidate = self.try_merge(existing, residual)
                if candidate is not None and (
                    best is None or candidate.occupancy > best.occupancy
                ):
                    best, best_idx = candidate, i
            if best is not None:
                merged[best_idx] = best
            else:
                merged.append(residual)
        return merged

    # --- entry point (ref squishyBinPacking, nexus.py:129) -----------------
    def plan(self, sessions: List[Session]) -> List[NodePlan]:
        active = [s for s in sessions if s.rate_rps > 0]
        saturated, residues = self.schedule_saturate(active)
        residue_nodes = [
            n for s in residues if (n := self.residue_node(s)) is not None
        ]
        return saturated + self.merge_residues(residue_nodes)

    def chips_required(self, sessions: List[Session]) -> int:
        """Physical chips the plan consumes: each node costs its slice
        width (1 for classic single-chip nodes — unchanged there)."""
        return sum(n.chips for n in self.plan(sessions))


# --- LLM decode colocation (the control theory applied to decode) ----------
#
# The duty-cycle packer above time-slices ONE compiled program per model
# through a cycle; continuous-batching decode engines instead run all the
# time, so their cost model is a COMPUTE FRACTION plus resident HBM:
# an engine with a measured per-substep latency `step_ms` at `num_slots`
# occupancy produces slots/step tokens per ms of chip time. Serving
# R tokens/s therefore needs fraction f = R*step_ms/(1000*slots) of the
# chip, and a co-tenant set fits iff fractions sum under a headroom and
# resident footprints sum under the HBM budget — the same
# admissibility-from-measured-tables discipline as the reference's
# squishyBinPacking (293-project/src/nexus.py:129-296), with the decode
# tables of profiles.decode_profiler as ground truth.


@dataclass(frozen=True)
class LLMSession:
    """One LLM's decode serving contract (the decode analogue of
    :class:`Session`)."""

    model: str
    rate_tok_s: float        # offered decode demand, tokens/s
    token_slo_ms: float      # per-token latency SLO (inter-token gap)
    # Minimum KV capacity (prompt + generation) a placement must hold:
    # shorter-capacity rows are cheaper on every axis, so without this
    # filter the picker would always "win" with caches too small for the
    # workload's real conversations (mirror of Session.seq_len).
    min_context: int = 0


@dataclass(frozen=True)
class LLMPlacement:
    model: str
    num_slots: int
    capacity: int            # KV capacity (max_len) of the chosen config
    step_ms: float
    compute_fraction: float
    hbm_bytes: int


def _pick_llm_row(
    session: LLMSession, profile: BatchProfile, headroom: float,
    hbm_budget: float,
) -> Optional[LLMPlacement]:
    """The measured (slots, capacity) config serving this session's rate
    within its token SLO at minimal COMPUTE FRACTION (ties: minimal HBM)
    — compute is the binding resource for colocation density; a config
    that halves the fraction for a few hundred KB of extra KV rows packs
    strictly more co-tenants per chip.

    Sharing stretches the observed inter-token gap to ~step_ms/f, so the
    SLO requires f >= step_ms/slo on top of the capacity requirement
    f >= rate*step/(1000*slots); a row is feasible iff that combined
    fraction fits under the headroom, its program fits the HBM budget,
    and its KV capacity covers the session's context. SLO feasibility
    uses worst-case latency (mean + 2*std, ``worst_latency_ms``) — the
    no-preemption discipline of the duty-cycle packer — while capacity
    throughput uses the mean.
    """
    best: Optional[LLMPlacement] = None
    for row in profile.rows:
        if row.spec != "off":
            # Spec rows cost one verify ROUND, not one step (ISSUE 13):
            # pricing them here without the expected_tokens_per_round
            # conversion would mis-unit step_ms/compute_fraction by up
            # to E(a,k)x. This packer plans plain decode engines; the
            # duty-cycle packer above is the spec-aware one.
            continue
        if row.latency_ms <= 0 or row.hbm_bytes <= 0:
            continue
        if row.hbm_bytes > hbm_budget:
            continue  # the budget filters per ROW, like saturate_row
        if row.seq_len < session.min_context:
            continue  # cache too small for the workload's conversations
        worst_ms = worst_latency_ms(row)
        if worst_ms > session.token_slo_ms:
            continue  # even a dedicated chip would miss the SLO
        f_capacity = (
            session.rate_tok_s * row.latency_ms
            / (1000.0 * row.batch_size)
        )
        f_slo = worst_ms / session.token_slo_ms
        f = max(f_capacity, f_slo)
        if f > headroom:
            continue
        cand = LLMPlacement(
            model=session.model,
            num_slots=row.batch_size,
            capacity=row.seq_len,
            step_ms=row.latency_ms,
            compute_fraction=f,
            hbm_bytes=row.hbm_bytes,
        )
        if (best is None
                or (cand.compute_fraction, cand.hbm_bytes)
                < (best.compute_fraction, best.hbm_bytes)):
            best = cand
    return best


def pack_llm_engines(
    sessions: List[LLMSession],
    decode_profiles: Dict[str, BatchProfile],
    hbm_budget_bytes: Optional[int] = None,
    compute_headroom: float = 0.85,
) -> List[List[LLMPlacement]]:
    """First-fit-decreasing colocation of decode engines onto chips.

    Returns one list of placements per chip. Raises ``ValueError`` when a
    session has no feasible measured config (missing table, SLO tighter
    than every measured step, or demand beyond a whole chip) — the caller
    must re-profile or relax, exactly like the duty-cycle packer's
    contract that only profiled shapes are schedulable.
    """
    cfg = get_config()
    budget = float(
        hbm_budget_bytes
        if hbm_budget_bytes is not None
        else cfg.hbm_budget_bytes * cfg.hbm_plan_fraction
    )
    placements: List[LLMPlacement] = []
    for session in sessions:
        profile = decode_profiles.get(session.model)
        if profile is None:
            raise ValueError(
                f"{session.model}: no decode profile — run the decode "
                "profiler (tools/run_profiles.py)"
            )
        placed = _pick_llm_row(session, profile, compute_headroom, budget)
        if placed is None:
            raise ValueError(
                f"{session.model}: no measured decode config serves "
                f"{session.rate_tok_s:.0f} tok/s within a "
                f"{session.token_slo_ms:.0f} ms token SLO "
                f"(min context {session.min_context}) under "
                f"{budget / 1e9:.1f} GB on one chip"
            )
        placements.append(placed)
    chips: List[List[LLMPlacement]] = []
    for p in sorted(placements, key=lambda p: -p.compute_fraction):
        for chip in chips:
            if (sum(c.compute_fraction for c in chip) + p.compute_fraction
                    <= compute_headroom
                    and sum(c.hbm_bytes for c in chip) + p.hbm_bytes
                    <= budget):
                chip.append(p)
                break
        else:
            chips.append([p])
    return chips
