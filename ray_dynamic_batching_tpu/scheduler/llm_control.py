"""Live LLM scheduler — token-rate monitoring, colocation replan, migration.

The decode-side control plane: the LLM analogue of
:class:`~ray_dynamic_batching_tpu.scheduler.control.LiveScheduler`
(itself modeled on the reference's ``NexusScheduler`` monitor/rebalance
loop, ``293-project/src/scheduler.py:602-929``). Per-model **token**
rates (decode demand, tokens/s) feed the colocation planner
(``scheduler.nexus.pack_llm_engines``); when a rate drifts past the
threshold the plan is recomputed and applied with minimal movement:
models keep their chip when their placement is unchanged, and a moved
model's old engine *drains* (in-flight sequences finish where they
started) while its successor admits from the model's shared queue on the
new chip — the decode version of the reference's live rebalance
(``293-project/src/scheduler.py:773-929``), with the drain discipline
replacing its transfer of queued work.

Execution rides :class:`~ray_dynamic_batching_tpu.engine.colocate.
ColocatedLLMEngines` (one per chip). Engines are built by a caller-
supplied factory so tests and deployments choose weights/sharding;
:func:`deployment_engine_factory` adapts a dict of
:class:`~ray_dynamic_batching_tpu.serve.llm.LLMDeployment` objects.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ray_dynamic_batching_tpu.engine.colocate import ColocatedLLMEngines
from ray_dynamic_batching_tpu.engine.decode import DecodeEngine
from ray_dynamic_batching_tpu.engine.queue import QueueManager, RequestQueue
from ray_dynamic_batching_tpu.engine.rates import RateRegistry
from ray_dynamic_batching_tpu.utils.concurrency import assert_owner
from ray_dynamic_batching_tpu.engine.request import Request, RequestDropped
from ray_dynamic_batching_tpu.profiles.table import BatchProfile
from ray_dynamic_batching_tpu.scheduler.audit import AuditLog, plan_diff
from ray_dynamic_batching_tpu.scheduler.nexus import (
    LLMPlacement,
    LLMSession,
    pack_llm_engines,
)
from ray_dynamic_batching_tpu.utils.config import get_config
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("llm_control")

EngineFactory = Callable[[str, LLMPlacement, RequestQueue, object],
                         DecodeEngine]


@dataclass
class LLMModelEntry:
    """Registered decode serving contract (ref models_config,
    ``293-project/src/scheduler.py:30-35`` — here per-token, not
    per-request)."""

    name: str
    token_slo_ms: float
    min_context: int = 0
    # Demand estimate for requests that don't carry max_new_tokens: the
    # rate registry counts TOKENS, so each submission records its decode
    # demand up front (the monitor sees offered load, not completions —
    # same inversion-avoidance as LiveScheduler.submit_request).
    tokens_per_request: int = 64


def deployment_engine_factory(
    deployments: Dict[str, "object"],
) -> EngineFactory:
    """Adapt ``{model_name: LLMDeployment}`` to the factory protocol:
    the planner's placement dictates (num_slots, capacity); the
    deployment supplies weights, buckets, and horizons."""

    def factory(model: str, placement: LLMPlacement,
                queue: RequestQueue, device: object) -> DecodeEngine:
        return deployments[model].build_engine(
            queue, device=device, max_len=placement.capacity,
            num_slots=placement.num_slots,
        )

    return factory


class LLMLiveScheduler:
    """The running decode control plane for a set of chips."""

    def __init__(
        self,
        decode_profiles: Dict[str, BatchProfile],
        chips: Sequence[ColocatedLLMEngines],
        engine_factory: EngineFactory,
        queues: Optional[QueueManager] = None,
        rates: Optional[RateRegistry] = None,
        compute_headroom: float = 0.85,
        hbm_budget_bytes: Optional[int] = None,
        metrics_path: Optional[str] = None,
        clock=time.monotonic,
    ) -> None:
        cfg = get_config()
        self.profiles = dict(decode_profiles)
        self.chips = list(chips)
        self.engine_factory = engine_factory
        self.queues = queues or QueueManager(max_len=cfg.max_queue_len)
        self.rates = rates or RateRegistry(window_s=cfg.rate_window_s,
                                           clock=clock)
        self.compute_headroom = compute_headroom
        self.hbm_budget_bytes = hbm_budget_bytes
        self.metrics_path = metrics_path
        self.monitoring_interval_s = cfg.monitoring_interval_s
        self.rate_threshold = cfg.rate_change_threshold
        self.rate_decrease_multiplier = cfg.rate_decrease_multiplier
        self._clock = clock
        self._models: Dict[str, LLMModelEntry] = {}
        self._current_plan: List[List[LLMPlacement]] = []
        self._closed = False
        # RLock: chip quarantine replans while already holding the lock.
        self._lock = threading.RLock()
        self.quarantined: List[ColocatedLLMEngines] = []
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.schedule_changes = 0
        self.migrations = 0
        self.engine_replacements = 0
        self.chip_quarantines = 0
        # Stalled-engine detection (the decode analogue of replica
        # health replacement): an engine WITH WORK whose heartbeat
        # hasn't moved in this long, on a chip whose executor is
        # demonstrably passing, is failing its turns — rebuild it.
        self.engine_stall_timeout_s = 60.0
        # Chip-level: an executor thread that stopped completing passes
        # is wedged inside a device call — its HBM cannot be freed
        # safely; the chip is quarantined and its models replanned onto
        # the survivors.
        self.chip_stall_timeout_s = 120.0
        self.schedule_log: List[Dict] = []
        # Structured replan ring (scheduler/audit.py): every decode-plane
        # decision — rate-triggered replans, quarantines, health rebuilds.
        self.audit = AuditLog("llm")

    # --- registration ------------------------------------------------------
    def register_model(
        self,
        name: str,
        token_slo_ms: float,
        min_context: int = 0,
        tokens_per_request: int = 64,
    ) -> None:
        if name not in self.profiles:
            raise KeyError(f"no decode profile for model {name!r} — run "
                           "the decode profiler (tools/run_profiles.py)")
        self._models[name] = LLMModelEntry(
            name, token_slo_ms, min_context, tokens_per_request
        )

    # --- ingress -----------------------------------------------------------
    def submit_request(self, request: Request) -> bool:
        entry = self._models.get(request.model)
        if entry is None:
            request.reject(
                KeyError(f"model {request.model!r} not registered")
            )
            return False
        if not self.chips:
            # Every chip quarantined: accepting would enqueue into
            # queues nothing can ever drain — fail fast instead.
            request.reject(RequestDropped(
                "no serving chips remain (all quarantined)"
            ))
            return False
        tokens = entry.tokens_per_request
        if isinstance(request.payload, dict):
            tokens = int(
                request.payload.get("max_new_tokens", tokens)
            )
        # Offered decode demand, recorded before the enqueue outcome
        # (drops must not suppress the scale-up signal).
        self.rates.record(request.model, n=max(1, tokens))
        return self.queues.queue(request.model).add_request(request)

    # --- planning ----------------------------------------------------------
    def _sessions_for(
        self, rates: Dict[str, float]
    ) -> List[LLMSession]:
        return [
            LLMSession(
                model=e.name,
                rate_tok_s=rates.get(e.name, 0.0),
                token_slo_ms=e.token_slo_ms,
                min_context=e.min_context,
            )
            for e in self._models.values()
            if rates.get(e.name, 0.0) > 0.0
        ]

    def _match_chips(
        self, plan: List[List[LLMPlacement]]
    ) -> List[Optional[List[LLMPlacement]]]:
        """Assign planned chips to executors maximizing kept models
        (minimal movement — the decode version of
        ``control.match_plans_to_engines``'s objective; overlap count
        stands in for transfer cost because every move costs a weight
        upload + compile here too)."""
        hosted = [set(c.models()) for c in self.chips]
        assignment: List[Optional[List[LLMPlacement]]] = (
            [None] * len(self.chips)
        )
        free = set(range(len(self.chips)))
        # Largest chips pick first so a big overlap isn't stolen by a
        # singleton plan.
        for planned in sorted(plan, key=len, reverse=True):
            names = {p.model for p in planned}
            best = max(
                free,
                key=lambda i: (len(names & hosted[i]), -len(hosted[i])),
            )
            assignment[best] = planned
            free.remove(best)
        return assignment

    def rebalance(
        self,
        rates: Optional[Dict[str, float]] = None,
        trigger: str = "manual",
    ) -> List[List[LLMPlacement]]:
        """Re-run colocation packing and migrate with minimal movement."""
        with self._lock:
            if self._closed:
                return self._current_plan
            if rates is None:
                rates = dict(self.rates.rates())
                # Cold-window readings are extrapolations (up to ~2x
                # inflated); for models already under contract, plan from
                # the last scheduled rate until the window has evidence —
                # otherwise the packer resizes fractions on noise even
                # though changed_models filtered the *trigger*. Models
                # with no baseline keep the raw reading (first placement
                # beats waiting half a window), and so does an EMPTY
                # window (span 0 = traffic stopped: resurrecting the old
                # contract would keep planning a dead model forever).
                min_span = self.rates.window_s / 2.0
                scheduled = self.rates.scheduled_rates()
                for m in list(rates):
                    span = self.rates.tracker(m).span_s()
                    if scheduled.get(m) and 0 < span < min_span:
                        rates[m] = scheduled[m]
            sessions = self._sessions_for(rates)
            try:
                plan = pack_llm_engines(
                    sessions,
                    self.profiles,
                    hbm_budget_bytes=self.hbm_budget_bytes,
                    compute_headroom=self.compute_headroom,
                ) if sessions else []
            except ValueError as e:
                # Infeasible demand: keep serving under the previous plan
                # rather than tearing engines down (the SLO viewer shows
                # red; the operator re-profiles or relaxes).
                logger.warning("rebalance infeasible, keeping plan: %s", e)
                self.audit.record(
                    trigger,
                    observed={"rates_tok_s": {k: round(v, 1)
                                              for k, v in rates.items()}},
                    note=f"infeasible, kept previous plan: {e}",
                )
                return self._current_plan
            if len(plan) > len(self.chips):
                if self._current_plan:
                    # Over capacity: applying a truncated plan would DRAIN
                    # the dropped models while submit_request keeps
                    # accepting their traffic — keep the previous
                    # (serving) assignment instead, exactly like the
                    # infeasible branch above.
                    logger.warning(
                        "plan needs %d chips but only %d executors — "
                        "keeping previous plan (capacity!)",
                        len(plan), len(self.chips),
                    )
                    self.audit.record(
                        trigger,
                        observed={"rates_tok_s": {
                            k: round(v, 1) for k, v in rates.items()}},
                        note=(f"over capacity ({len(plan)} chips needed, "
                              f"{len(self.chips)} available), kept "
                              "previous plan"),
                    )
                    return self._current_plan
                # Nothing is serving yet (first plan): a truncated plan
                # that serves len(chips) chips' worth of models beats an
                # empty one that serves nobody.
                logger.warning(
                    "plan needs %d chips but only %d executors — serving "
                    "the first %d planned chips (capacity!)",
                    len(plan), len(self.chips), len(self.chips),
                )
                plan = plan[: len(self.chips)]
            assignment = self._match_chips(plan)
            hosted_before = [sorted(c.models()) for c in self.chips]
            moved = self._apply(assignment)
            hosted_after = [
                sorted(p.model for p in (chip or [])) for chip in assignment
            ]
            self._current_plan = plan
            self.rates.mark_scheduled(rates)
            self.schedule_changes += 1
            self.migrations += moved
            self.audit.record(
                trigger,
                observed={"rates_tok_s": {k: round(v, 1)
                                          for k, v in rates.items()}},
                inputs={
                    # The committed decode-table rows the packer sized from.
                    "placements": [
                        {"model": p.model, "slots": p.num_slots,
                         "capacity": p.capacity,
                         "compute_fraction": round(p.compute_fraction, 3)}
                        for chip in plan for p in chip
                    ],
                },
                before=[", ".join(m) for m in hosted_before],
                after=[", ".join(m) for m in hosted_after],
                diff=plan_diff(hosted_before, hosted_after),
                # Every engine move costs a weight upload + compiles; the
                # moved count is the decode plane's migration cost unit.
                migration_cost=float(moved),
            )
            self.schedule_log.append({
                "ts": self._clock(),
                "rates_tok_s": {k: round(v, 1) for k, v in rates.items()},
                "chips": [
                    [
                        f"{p.model}(slots={p.num_slots}, cap={p.capacity}, "
                        f"f={p.compute_fraction:.2f})"
                        for p in (chip or [])
                    ]
                    for chip in assignment
                ],
                "moved_engines": moved,
            })
            logger.info(
                "rebalance #%d: %d chips, %d engine moves for rates %s",
                self.schedule_changes, len(plan), moved,
                {k: round(v, 1) for k, v in rates.items()},
            )
            return plan

    def _apply(
        self, assignment: List[Optional[List[LLMPlacement]]]
    ) -> int:
        """Diff each chip's desired placement set against what it hosts;
        drain leavers, build/attach joiners. Returns engines moved."""
        moved = 0
        apply_deadline = time.monotonic() + 60.0  # whole-pass drain budget
        desired_by_chip: List[Dict[str, LLMPlacement]] = [
            {p.model: p for p in (chip or [])} for chip in assignment
        ]
        # Detach pass first: a model moving chips must stop admitting on
        # its old chip before the new engine attaches, so the shared
        # queue never feeds two admitting engines.
        drain_events: Dict[tuple, threading.Event] = {}
        for ci, (chip, desired) in enumerate(
            zip(self.chips, desired_by_chip)
        ):
            current = chip.placements()
            for model in chip.models():
                cur = current.get(model)
                want = desired.get(model)
                if want is None or not self._same_shape(cur, want):
                    drain_events[(ci, model)] = chip.detach(
                        model, drain=True
                    )
                    moved += 1
        for ci, (chip, desired) in enumerate(
            zip(self.chips, desired_by_chip)
        ):
            hosted = set(chip.models())
            for model, placement in desired.items():
                if model in hosted:
                    continue
                # Same-chip shape change: wait for the predecessor's HBM
                # to come back (drain completes, buffers released) before
                # building the successor — a chip packed near the budget
                # line cannot hold both copies of the weights + KV at
                # once. Only meaningful when the executor loop is running
                # to actually drive the drain. Bounded by ONE deadline
                # across the whole apply pass (not per model — _apply
                # runs under _lock, and shutdown/monitor block on that
                # lock), and aborted early when shutdown signals _stop;
                # on expiry it degrades to the transient double
                # residency instead of freezing the control plane.
                ev = drain_events.get((ci, model))
                if ev is not None and chip.running:
                    while (not ev.is_set()
                           and not self._stop.is_set()
                           and time.monotonic() < apply_deadline):
                        ev.wait(timeout=0.25)
                    if not ev.is_set():
                        logger.warning(
                            "%s: %s drain slow — attaching successor "
                            "with predecessor still resident",
                            chip.name, model,
                        )
                engine = self.engine_factory(
                    model, placement, self.queues.queue(model), chip.device
                )
                chip.attach(model, engine, placement)
        return moved

    @staticmethod
    def _same_shape(cur: Optional[LLMPlacement],
                    want: LLMPlacement) -> bool:
        """An engine survives a replan iff its compiled shapes match the
        new placement; fraction changes alone don't force a rebuild."""
        return (
            cur is not None
            and cur.num_slots == want.num_slots
            and cur.capacity == want.capacity
        )

    # --- health: stalled-engine replacement --------------------------------
    def check_engine_health(
        self, stall_timeout_s: Optional[float] = None
    ) -> int:
        """Replace engines that have work but whose turns stopped
        succeeding (heartbeat refreshes only on completed turns — a
        repeatedly-raising engine reads stale while its queue rots).
        Only chips whose executor loop is PROVABLY passing are
        considered: a stale heartbeat on a non-passing chip means the
        executor itself is stuck (possibly inside this engine's device
        call) and releasing buffers under it would be a use-after-free —
        that failure needs chip-level quarantine, not an engine swap.
        The swap itself happens on the executor thread at a pass
        boundary (``ColocatedLLMEngines.replace``), for the same reason.
        Ref: the replica heal path's stall contract
        (``serve/replica.py::healthy`` / controller replacement)."""
        timeout = (stall_timeout_s if stall_timeout_s is not None
                   else self.engine_stall_timeout_s)
        now = time.monotonic()
        replaced = 0
        with self._lock:
            if self._closed:
                return 0
            self._quarantine_wedged_chips(now)
            for chip in self.chips:
                if chip._thread is not None and not chip.running:
                    # The executor thread DIED (exited/crashed) rather
                    # than wedging: engine state is intact and no device
                    # call is in flight, so a restart is safe — without
                    # it the chip would be invisible to both health
                    # paths (they key on running executors).
                    logger.error(
                        "%s: executor thread died — restarting",
                        chip.name,
                    )
                    chip.start()
                    continue
                if not chip.running:
                    continue
                if now - chip.last_pass_monotonic > min(5.0, timeout):
                    continue  # executor not passing: not safe to swap
                placements = chip.placements()
                for model in chip.models():
                    engine = chip.engine_for(model)
                    if engine is None:
                        continue
                    has_work = (
                        engine.active_slots > 0
                        or len(engine.queue) > 0
                    )
                    if not has_work:
                        continue
                    if now - engine.last_heartbeat < timeout:
                        continue
                    placement = placements.get(model)
                    logger.warning(
                        "%s on %s: stalled %.0fs with work — rebuilding",
                        model, chip.name, now - engine.last_heartbeat,
                    )
                    successor = self.engine_factory(
                        model, placement, self.queues.queue(model),
                        chip.device,
                    )
                    chip.replace(model, successor, placement)
                    replaced += 1
                    self.engine_replacements += 1
                    self.audit.record(
                        "health",
                        key=model,
                        observed={
                            "stalled_s": round(
                                now - engine.last_heartbeat, 1),
                            "chip": chip.name,
                        },
                        diff={"engine_rebuilt": model},
                        migration_cost=1.0,
                        note="stalled engine with work rebuilt in place",
                    )
        return replaced

    def _quarantine_wedged_chips(self, now: float) -> None:
        """A RUNNING executor that stopped completing passes is wedged
        inside a device call: its engines' buffers can never be freed
        safely (the call may still be touching them), so the chip is
        written off — leaked deliberately, loudly — and its models
        replan onto the surviving chips. In-flight slot futures are
        rejected host-side (Request.reject/fulfill tolerate the wedged
        call completing later); queued work lives in the SHARED queues
        and flows to the replacements. Caller holds the lock."""
        assert_owner(self._lock)
        wedged = [
            chip for chip in self.chips
            if chip.running
            and (now - chip.last_progress_monotonic()
                 > self.chip_stall_timeout_s)
        ]
        for chip in wedged:
            logger.error(
                "%s: executor wedged (%.0fs since last pass) — "
                "quarantining the chip; its HBM is written off",
                chip.name, now - chip.last_pass_monotonic,
            )
            # Stop admissions if/when the wedged call ever returns: the
            # loop checks _run before the next pass and exits, so the
            # dead chip can never race its replacements for queue work.
            chip.stop(timeout_s=0.1)
            self.chips.remove(chip)
            self.quarantined.append(chip)
            self.chip_quarantines += 1
            self.audit.record(
                "quarantine",
                observed={
                    "chip": chip.name,
                    "stalled_s": round(
                        now - chip.last_pass_monotonic, 1),
                },
                diff={"chip_quarantined": chip.name,
                      "models_displaced": sorted(
                          m for m, _ in chip.hosted_engines())},
                note="wedged executor — HBM written off, models replanned "
                     "onto survivors",
            )
            # EVERY resident engine, draining predecessors included —
            # their drains can never finish on a wedged chip, and their
            # slots hold real futures too.
            for model, engine in chip.hosted_engines():
                exc = RequestDropped(
                    f"{model}: chip {chip.name} quarantined mid-flight"
                )
                for slot in getattr(engine, "_slots", []):
                    req = getattr(slot, "request", None)
                    if req is not None and not getattr(slot, "free", True):
                        req.reject(exc)
                # Requests the wedged _admit popped but never slotted —
                # in neither the queue nor a slot; without this they
                # hang forever (and the replacements can't serve them:
                # they're gone from the shared queue).
                for req in list(getattr(engine, "_admitting_batch", [])):
                    req.reject(exc)
        if wedged and not self._closed:
            # The previous plan references dead chips — keeping it (the
            # over-capacity / infeasible degradation branches) would
            # blackhole their models while submit_request keeps
            # accepting traffic. Invalidate UNCONDITIONALLY (even with
            # zero survivors, a stale truthy plan would poison every
            # later degradation branch), then replan onto whatever
            # survives (truncated if need be).
            self._current_plan = []
            if self.chips:
                self.rebalance(trigger="quarantine")

    # --- monitor loop ------------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitoring_interval_s):
            try:
                self.check_engine_health()
                changed = self.rates.changed_models(
                    self.rate_threshold, self.rate_decrease_multiplier,
                    # Half a window of evidence before a replan: engine
                    # migration is expensive (weight upload + compiles),
                    # so cold-start extrapolation must not trigger it.
                    min_span_s=self.rates.window_s / 2.0,
                )
                if changed:
                    logger.info("token-rate change detected: %s",
                                {k: round(v, 1) for k, v in changed.items()})
                    self.rebalance(trigger="rate_change")
                if self.metrics_path:
                    self.write_metrics()
            except Exception:  # noqa: BLE001
                logger.exception("llm monitor iteration failed")

    def start_monitoring(self) -> None:
        if self._monitor is not None:
            return
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="rdb-llm-monitor", daemon=True
        )
        self._monitor.start()

    def stop_monitoring(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None

    def shutdown(self, timeout_s: float = 5.0) -> None:
        self.stop_monitoring()
        # Serialize with any in-flight rebalance (the monitor join above
        # can time out mid-_apply): taking the lock waits it out, and the
        # closed flag makes any later stragglers no-ops — otherwise a
        # straggling _apply would attach fresh engines to chips whose
        # loops are already stopped, leaking their HBM.
        with self._lock:
            self._closed = True
        for chip in self.chips:
            chip.shutdown(timeout_s)
        for chip in self.quarantined:
            # Best-effort: a still-wedged loop keeps its buffers (the
            # executor's own shutdown guard); an unwedged one cleans up.
            chip.shutdown(timeout_s=0.5)

    # --- observability -----------------------------------------------------
    def snapshot(self) -> Dict:
        return {
            "time": self._clock(),
            "rates_tok_s": self.rates.rates(),
            "scheduled_rates_tok_s": self.rates.scheduled_rates(),
            "queues": self.queues.stats(),
            "chips": [c.describe() for c in self.chips],
            "busy_fractions": [c.busy_fractions() for c in self.chips],
            "schedule_changes": self.schedule_changes,
            "migrations": self.migrations,
            "engine_replacements": self.engine_replacements,
            "chip_quarantines": self.chip_quarantines,
            "quarantined": [c.name for c in self.quarantined],
            "audit": self.audit.to_dicts(last=20),
        }

    def write_metrics(self) -> None:
        with open(self.metrics_path, "w") as f:
            json.dump(self.snapshot(), f, indent=2)

    def render_status(self) -> str:
        """Terminal SLO status — the same table renderer the vision
        loop, state CLI, and dashboard share (rates shown in tok/s)."""
        from ray_dynamic_batching_tpu.state import render_queue_table

        return render_queue_table(self.queues.stats(), self.rates.rates())
