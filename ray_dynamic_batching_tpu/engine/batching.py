"""Batching disciplines behind one policy interface (SURVEY.md §7 stage 3).

Two policies from the reference, unified:

- :class:`NexusFixedBatch` — profile-driven fixed batch with staleness
  discard, as executed by the duty-cycle worker
  (``293-project/src/scheduler.py:274-289``): take up to the scheduled batch
  size immediately; the *scheduler* chose the size, the queue enforces
  deadlines.
- :class:`OpportunisticBatch` — Ray Serve's ``@serve.batch`` semantics
  (``python/ray/serve/batching.py:146-197``): return when ``max_batch_size``
  requests are waiting OR ``batch_wait_timeout_s`` has elapsed since the
  FIRST queued request; knobs are runtime-tunable (ref ``batching.py:369-386``).

Both return concrete request lists; padding-to-bucket is the engine's job
(the policy decides *membership*, the compiled-program cache decides *shape*).
"""

from __future__ import annotations

import abc
from typing import List, Optional

from ray_dynamic_batching_tpu.engine.queue import RequestQueue
from ray_dynamic_batching_tpu.engine.request import Request, now_ms
from ray_dynamic_batching_tpu.utils.tracing import link_to, tracer


class BatchPolicy(abc.ABC):
    @abc.abstractmethod
    def next_batch(self, queue: RequestQueue) -> List[Request]:
        """Return the next batch to execute (possibly empty)."""

    def describe(self) -> str:
        return type(self).__name__


class NexusFixedBatch(BatchPolicy):
    """Scheduled fixed-size batch; never waits (the duty cycle is the wait)."""

    def __init__(self, batch_size: int, expected_latency_ms: float = 0.0,
                 discard_stale: bool = True):
        self.batch_size = batch_size
        self.expected_latency_ms = expected_latency_ms
        self.discard_stale = discard_stale

    def next_batch(self, queue: RequestQueue) -> List[Request]:
        return queue.get_batch(
            self.batch_size,
            expected_latency_ms=self.expected_latency_ms,
            discard_stale=self.discard_stale,
        )

    def describe(self) -> str:
        return f"NexusFixedBatch(b={self.batch_size})"


class OpportunisticBatch(BatchPolicy):
    """Size-or-timeout batching (ref _BatchQueue.wait_for_batch,
    serve/batching.py:146-197)."""

    def __init__(
        self,
        max_batch_size: int = 32,
        batch_wait_timeout_s: float = 0.01,
        expected_latency_ms: float = 0.0,
    ):
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.expected_latency_ms = expected_latency_ms

    # runtime-tunable knobs (ref batching.py:369-386)
    def set_max_batch_size(self, n: int) -> None:
        self.max_batch_size = n

    def set_batch_wait_timeout_s(self, t: float) -> None:
        self.batch_wait_timeout_s = t

    def next_batch(self, queue: RequestQueue) -> List[Request]:
        # Blocks on the queue's condition variable; deadline anchored at the
        # FIRST request's arrival, not at poll time.
        wait_start = now_ms()
        queue.wait_for_batch(self.max_batch_size, self.batch_wait_timeout_s)
        batch = queue.get_batch(
            self.max_batch_size,
            expected_latency_ms=self.expected_latency_ms,
        )
        if batch and tracer().enabled:
            # Membership decision as its own span: how long the size-or-
            # timeout discipline held the batch open, linked to every
            # member request (fan-in — parent/child cannot express it).
            # Start is clamped to the FIRST member's enqueue: idle-queue
            # time before any request existed is not formation hold.
            first_in = min(
                (r.enqueue_ms or r.arrival_ms) for r in batch
            )
            tracer().record_span(
                "batch.form",
                start_ms=max(wait_start, first_in),
                end_ms=now_ms(),
                links=[link_to(r.trace_ctx) for r in batch],
                policy=self.describe(),
                model=queue.model,
                lane=queue.model,
                size=len(batch),
            )
        return batch

    def describe(self) -> str:
        return (
            f"OpportunisticBatch(max={self.max_batch_size}, "
            f"wait={self.batch_wait_timeout_s * 1000:.0f}ms)"
        )
