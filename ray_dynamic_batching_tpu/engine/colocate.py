"""Colocated decode execution — N continuous-batching engines on ONE chip.

``scheduler.nexus.pack_llm_engines`` plans which decode engines share a
chip by profiled compute fraction + resident HBM; this module is the
execution side of that plan — the decode analogue of the duty-cycle
executor (``engine/worker.py``), mirroring how the reference *executes*
its packed schedules rather than only computing them
(``293-project/src/scheduler.py:525-584``).

One driver thread interleaves the co-resident engines at **horizon
granularity**: each engine's turn is one admission pass plus one compiled
scan (``DecodeEngine._step`` — ``decode_horizon`` substeps per dispatch).
A compiled scan cannot be preempted mid-flight, so the scan IS the
scheduling quantum, exactly like the duty-cycle packer's no-preemption
occupancy discipline (``scheduler/nexus.py:86-88``).

Turns are **deficit-weighted by the planner's fractions**: each engine
banks credit in proportion to its placement's ``compute_fraction`` as
chip time elapses and pays its measured turn cost when it runs, so under
sustained backlog engine *i*'s share of chip time converges to the
fraction the plan ADMITTED it at (``scheduler/nexus.py:326-376``) — not
to the accidental ``step_i / sum(step_j)`` ratio plain round-robin
yields. Idle engines don't bank (their credit resets), so the executor
stays work-conserving: an engine with the chip's only backlog takes the
whole chip. :meth:`busy_fractions` exposes the measured shares so tests
can hold the plan to the execution.

Engines attach/detach live (the LLM control loop migrates models between
chips as token rates shift). Detach drains by default: the engine stops
admitting immediately — its request queue is the *model's* shared queue,
so new arrivals flow to wherever the model runs next — while in-flight
sequences finish here; the engine's HBM (params + KV cache) is released
only once its last slot completes.
"""

from __future__ import annotations

import collections
import statistics
import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax

from ray_dynamic_batching_tpu.engine.decode import DecodeEngine
from ray_dynamic_batching_tpu.engine.request import RequestDropped
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("colocate")

BUSY_FRACTION = m.Gauge(
    "rdb_colocate_busy_fraction",
    "Measured share of executor wall time per co-resident engine "
    "(the ground truth the planner's compute_fraction predicts)",
    tag_keys=("chip", "model"),
)


@dataclass
class HostedEngine:
    """One co-resident engine plus its execution accounting."""

    model: str
    engine: DecodeEngine
    placement: Any = None          # LLMPlacement the planner assigned (if any)
    draining: bool = False
    busy_ms: float = 0.0           # wall time spent inside this engine's turns
    credit_ms: float = 0.0         # deficit round-robin balance
    released: threading.Event = field(default_factory=threading.Event)

    @property
    def weight(self) -> float:
        """Planned share of the chip: the placement's compute fraction,
        or 1.0 (equal split after normalization) when unplanned."""
        f = getattr(self.placement, "compute_fraction", None)
        return float(f) if f else 1.0

    def has_work(self) -> bool:
        if self.engine.active_slots > 0:
            return True
        return not self.draining and len(self.engine.queue) > 0


class ColocatedLLMEngines:
    """Round-robin interleaved execution of decode engines on one chip.

    Engines must arrive *un-started* (their own loop thread replaced by
    this executor's); all co-residents share the executor's device, so
    the single ``jax.default_device`` scope covers every dispatch.
    """

    def __init__(
        self,
        device: Optional[Any] = None,
        name: str = "chip0",
        idle_wait_s: float = 0.002,
    ) -> None:
        self.device = device
        self.name = name
        self.idle_wait_s = idle_wait_s
        self._hosted: Dict[str, HostedEngine] = {}
        self._lock = threading.RLock()
        self._run = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wall_ms = 0.0
        # Recent turn costs (median), for the credit clamp: a first-turn
        # XLA compile can cost seconds — charged raw, the debtor would
        # starve for hundreds of turns repaying chip time no tenant will
        # miss. Bounding credits to a few TYPICAL turns keeps transients
        # short while leaving long-run shares exactly weight-proportional.
        self._recent_costs: collections.deque = collections.deque(maxlen=32)
        # Between-chunk yields (long-prompt admissions): depth-1 guard +
        # nested-cost ledger so the yielding engine isn't billed for the
        # co-tenant scans that ran inside its turn.
        self._yielding = False
        self._nested_ms = 0.0
        # Deferred engine swaps (health-path replacement): applied at
        # the next PASS BOUNDARY by the executor thread itself, so a
        # wedged/failing engine is never released while a turn might be
        # inside it. Timestamp of the last completed pass is the
        # executor-liveness signal health checks key on.
        self._pending_replacements: Dict[str, Tuple[DecodeEngine, Any]] = {}
        self.last_pass_monotonic = time.monotonic()

    # --- membership (called by the control loop, any thread) ---------------
    def attach(self, model: str, engine: DecodeEngine,
               placement: Any = None) -> None:
        if engine._thread is not None:
            raise ValueError(
                f"{model}: engine already runs its own loop — colocated "
                "engines are stepped by the executor"
            )
        with self._lock:
            if model in self._hosted and not self._hosted[model].draining:
                raise ValueError(f"{model}: already hosted on {self.name}")
            # A draining predecessor keeps finishing under a temporary key
            # so its in-flight sequences aren't orphaned by the successor.
            if model in self._hosted:
                old = self._hosted.pop(model)
                self._hosted[f"{model}@draining{id(old)}"] = old
            hosted = HostedEngine(model, engine, placement)
            self._hosted[model] = hosted
            # Long-prompt admissions yield to co-tenants between chunks.
            engine.interleave_hook = (
                lambda h=hosted: self._yield_turn(h)
            )
        logger.info("%s: attached %s (slots=%d, cap=%d)", self.name, model,
                    engine.num_slots, engine.max_len)

    def replace(self, model: str, engine: DecodeEngine,
                placement: Any = None) -> None:
        """Health-path swap: the READY successor (built + warmed by the
        control loop) takes over at the next pass boundary — executed on
        the executor thread, so the failing predecessor is released
        outside any possible turn into it. Its in-flight requests are
        rejected (heal semantics: a wedged engine's slots are lost, the
        shared queue's backlog moves to the successor)."""
        if engine._thread is not None:
            raise ValueError(
                f"{model}: replacement engine already runs its own loop"
            )
        with self._lock:
            prior = self._pending_replacements.pop(model, None)
            self._pending_replacements[model] = (engine, placement)
        if prior is not None:
            # A second pend before the pass boundary: the dropped
            # successor's warm buffers must not leak.
            prior[0].release_buffers()

    def _apply_replacements(self) -> None:
        with self._lock:
            pending = self._pending_replacements
            self._pending_replacements = {}
        for model, (engine, placement) in pending.items():
            with self._lock:
                old = self._hosted.get(model)
                if old is None or old.draining:
                    # The model left this chip between pend and pass
                    # boundary (rebalance migrated or drained it):
                    # installing the successor would resurrect an
                    # off-plan SECOND admitter against the shared queue.
                    stale = engine
                else:
                    stale = None
                    self._hosted.pop(model, None)
                    self._release(old)
                    hosted = HostedEngine(model, engine, placement)
                    engine.interleave_hook = (
                        lambda h=hosted: self._yield_turn(h)
                    )
                    self._hosted[model] = hosted
            if stale is not None:
                stale.release_buffers()
                logger.warning(
                    "%s: dropped stale replacement for %s (model no "
                    "longer hosted here)", self.name, model,
                )
            else:
                logger.warning(
                    "%s: replaced %s (health path; slots=%d, cap=%d)",
                    self.name, model, engine.num_slots, engine.max_len,
                )

    def detach(self, model: str, drain: bool = True) -> threading.Event:
        """Stop admitting for ``model`` on this chip. With ``drain`` the
        in-flight sequences finish first; the returned event is set once
        the engine's buffers are released."""
        with self._lock:
            pending = self._pending_replacements.pop(model, None)
            hosted = self._hosted.get(model)
            if hosted is None:
                ev = threading.Event()
                ev.set()
                if pending is not None:
                    pending[0].release_buffers()
                return ev
            hosted.draining = True
            if not drain:
                self._release(hosted)
                self._hosted.pop(model, None)
        if pending is not None:
            # A detach cancels any queued health swap for the model —
            # its successor must neither resurrect the model here nor
            # leak its warm buffers.
            pending[0].release_buffers()
        return hosted.released

    def _release(self, hosted: HostedEngine) -> None:
        hosted.engine.interleave_hook = None
        hosted.engine.abort_active(
            RequestDropped(f"{hosted.model} detached from {self.name}")  # rdb-lint: disable=shed-accounting (detach is a replan decision already recorded in the scheduler audit ring; abort_active resolves each slot future, and the decode engine's slot stats count the aborts)
        )
        hosted.engine.release_buffers()
        hosted.released.set()
        # A departed model must not keep reporting its last share.
        BUSY_FRACTION.set(
            0.0, tags={"chip": self.name, "model": hosted.model}
        )
        logger.info("%s: released %s", self.name, hosted.model)

    def models(self) -> List[str]:
        with self._lock:
            return [m for m, h in self._hosted.items() if not h.draining]

    def placements(self) -> Dict[str, Any]:
        with self._lock:
            return {
                m: h.placement
                for m, h in self._hosted.items() if not h.draining
            }

    def engine_for(self, model: str) -> Optional[DecodeEngine]:
        with self._lock:
            h = self._hosted.get(model)
            return h.engine if h is not None and not h.draining else None

    def hosted_engines(self) -> List[Tuple[str, DecodeEngine]]:
        """EVERY resident engine — including draining predecessors,
        whose in-flight slots a chip quarantine must still reject."""
        with self._lock:
            return [
                (h.model, h.engine) for h in self._hosted.values()
                if not h.released.is_set()
            ]

    def last_progress_monotonic(self) -> float:
        """Most recent sign of life: pass starts OR completed engine
        turns OR fresh attaches (engines stamp their heartbeat at
        construction). Wedge detection keys on this rather than pass
        starts alone, so a legitimately long first-turn compile on a
        freshly built engine gets its full grace window instead of
        reading as a wedge."""
        with self._lock:
            beats = [
                h.engine.last_heartbeat for h in self._hosted.values()
            ]
        return max([self.last_pass_monotonic] + beats)

    # --- execution ---------------------------------------------------------
    def _turn(self, hosted: HostedEngine) -> Tuple[bool, float]:
        """One scheduling quantum for one engine: admit (unless draining),
        then at most one compiled scan. Returns (compute ran, cost ms) —
        cost EXCLUDES co-tenant scans that ran via between-chunk yields
        inside this turn (they bill their own engines)."""
        t0 = time.perf_counter()
        nested0 = self._nested_ms
        engine = hosted.engine
        stepped = False
        with engine._device_ctx():
            if not hosted.draining:
                engine._admit()
            if engine._active_mask.any():
                engine._step()
                stepped = True
        engine.last_heartbeat = time.monotonic()
        cost = (time.perf_counter() - t0) * 1000.0
        cost = max(0.0, cost - (self._nested_ms - nested0))
        hosted.busy_ms += cost
        return stepped, cost

    def _yield_turn(self, yielding: HostedEngine) -> None:
        """Between-chunk yield from a long admission: ONE step-only scan
        for the most-owed co-tenant with active work. Admission is not
        run here (a co-tenant's own long fill inside the yield would
        re-monopolize the chip); depth-1 guard stops recursion."""
        if self._yielding:
            return
        self._yielding = True
        try:
            with self._lock:
                others = [
                    h for h in self._hosted.values()
                    if h is not yielding and not h.released.is_set()
                ]
            workable = [h for h in others if h.engine.active_slots > 0]
            if not workable:
                return
            chosen = max(workable, key=lambda h: h.credit_ms)
            t0 = time.perf_counter()
            with chosen.engine._device_ctx():
                chosen.engine._step()
            chosen.engine.last_heartbeat = time.monotonic()
            cost = (time.perf_counter() - t0) * 1000.0
            chosen.busy_ms += cost
            self._nested_ms += cost
            pool = workable + [yielding]
            total_w = sum(h.weight for h in pool)
            for h in pool:
                h.credit_ms += cost * (h.weight / total_w)
            chosen.credit_ms -= cost
        except Exception:  # noqa: BLE001 — a co-tenant must not kill the fill
            logger.exception("%s: yield turn failed", self.name)
        finally:
            self._yielding = False

    def _finalize_drains(self, hosted) -> None:
        for key, h in hosted:
            if h.draining and h.engine.active_slots == 0:
                with self._lock:
                    self._release(h)
                    # Pop by identity: a concurrent attach may have put a
                    # REPLACEMENT engine under this snapshot's key (the
                    # drained predecessor was renamed) — popping by key
                    # alone would silently unhost the successor.
                    if self._hosted.get(key) is h:
                        self._hosted.pop(key, None)
                    else:
                        for k, v in list(self._hosted.items()):
                            if v is h:
                                self._hosted.pop(k, None)

    def _pass(self) -> bool:
        """One deficit-weighted quantum: run the most-owed engine that
        has work, then distribute its measured cost as credit in
        proportion to the backlogged engines' planned fractions."""
        self._apply_replacements()
        self.last_pass_monotonic = time.monotonic()
        with self._lock:
            hosted = list(self._hosted.items())
        self._finalize_drains(hosted)
        workable = []
        for key, h in hosted:
            if h.released.is_set():
                continue
            if h.has_work():
                workable.append(h)
            else:
                # Idle engines don't bank credit: a tenant returning
                # after a lull must not monopolize the chip repaying a
                # debt nobody accrued against real work.
                h.credit_ms = 0.0
        if not workable:
            return False
        chosen = max(workable, key=lambda h: h.credit_ms)
        try:
            stepped, cost = self._turn(chosen)
        except Exception:  # noqa: BLE001 — one engine must not kill the chip
            logger.exception("%s: turn failed for %s", self.name,
                             chosen.model)
            # Charge the failed turn a typical cost: with credits
            # untouched the max-credit pick would select the SAME broken
            # engine forever and starve every co-tenant (round-robin's
            # one virtue this scheduler must keep).
            penalty = max(
                statistics.median(self._recent_costs)
                if self._recent_costs else 1.0,
                1.0,
            )
            chosen.credit_ms -= penalty
            time.sleep(0.01)  # rdb-lint: disable=event-loop-blocking (failed-turn backoff on the colocation executor's own thread)
            return False
        total_w = sum(h.weight for h in workable)
        for h in workable:
            h.credit_ms += cost * (h.weight / total_w)
        chosen.credit_ms -= cost
        self._recent_costs.append(cost)
        cap = 8.0 * max(statistics.median(self._recent_costs), 0.1)
        for h in workable:
            h.credit_ms = max(-cap, min(cap, h.credit_ms))
        return stepped

    def step_once(self) -> bool:
        """Test/driver hook: one pass without the thread."""
        t0 = time.perf_counter()
        progressed = self._pass()
        with self._lock:
            self._wall_ms += (time.perf_counter() - t0) * 1000.0
        return progressed

    def run_until_idle(self, timeout_s: float = 60.0) -> None:
        """Drive passes until every engine's queue and slots are empty."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            progressed = self.step_once()
            with self._lock:
                idle = all(
                    h.engine.active_slots == 0 and len(h.engine.queue) == 0
                    for h in self._hosted.values()
                )
            if idle and not progressed:
                return
        raise TimeoutError(f"{self.name}: colocated engines did not drain")

    def _loop(self) -> None:
        ctx = (
            jax.default_device(self.device)
            if self.device is not None else nullcontext()
        )
        with ctx:
            while self._run.is_set():
                t0 = time.perf_counter()
                try:
                    progressed = self._pass()
                except Exception:  # noqa: BLE001 — loop must not die silently
                    logger.exception("%s: pass failed", self.name)
                    progressed = False
                    time.sleep(0.05)  # rdb-lint: disable=event-loop-blocking (pass error backoff on the colocation executor's own thread)
                with self._lock:
                    self._wall_ms += (time.perf_counter() - t0) * 1000.0
                if not progressed:
                    time.sleep(self.idle_wait_s)  # rdb-lint: disable=event-loop-blocking (idle wait on the colocation executor's own thread)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self._thread is not None:
            if self._thread.is_alive():
                return
            # A previously wedged loop has since exited (stop() left the
            # handle so callers could see it lived): safe to respawn.
            self._thread = None
        self._run.set()
        self._thread = threading.Thread(
            target=self._loop, name=f"colocate-{self.name}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._run.clear()
        if self._thread is not None:
            self._thread.join(timeout_s)
            if self._thread.is_alive():
                # Wedged in a device call: leave the handle so callers can
                # see the thread still lives (buffer release must not
                # happen under it).
                logger.warning("%s: loop did not exit in %.1fs", self.name,
                               timeout_s)
            else:
                self._thread = None

    def shutdown(self, timeout_s: float = 5.0) -> None:
        """Stop the loop and abort/release every hosted engine. If the
        loop is wedged in a device call the buffers are NOT released —
        a still-running scan may be touching them, and dropping the
        references mid-flight trades a leak for a use-after-free-style
        crash (same discipline as LLMReplica.stop)."""
        self.stop(timeout_s)
        if self.running:
            logger.warning(
                "%s: loop still alive after stop — leaking hosted "
                "engines' buffers rather than releasing under a live "
                "scan", self.name,
            )
            return
        with self._lock:
            for h in list(self._hosted.values()):
                self._release(h)
            self._hosted.clear()
            pending = list(self._pending_replacements.values())
            self._pending_replacements.clear()
        for engine, _ in pending:
            # Never-installed successors hold warm weights + KV.
            engine.release_buffers()

    # --- accounting ---------------------------------------------------------
    def busy_fractions(self) -> Dict[str, float]:
        """Measured share of executor wall time each engine consumed —
        the ground truth the planner's ``compute_fraction`` predicts.
        Only REAL model names export to the gauge: the synthetic
        ``model@draining<id>`` keys minted per migration would grow the
        metric's tag cardinality without bound on a long-running
        deployment (and the gauge registry never evicts)."""
        with self._lock:
            wall = max(self._wall_ms, 1e-9)
            out = {mk: h.busy_ms / wall for mk, h in self._hosted.items()}
            hosted = {
                mk: h.model for mk, h in self._hosted.items()
                if not h.draining
            }
        for mk, model in hosted.items():
            BUSY_FRACTION.set(out[mk],
                              tags={"chip": self.name, "model": model})
        return out

    def reset_accounting(self) -> None:
        with self._lock:
            self._wall_ms = 0.0
            for h in self._hosted.values():
                h.busy_ms = 0.0
                h.credit_ms = 0.0

    @property
    def active(self) -> bool:
        with self._lock:
            return any(
                getattr(h.engine, "busy", h.engine.active_slots > 0)
                for h in self._hosted.values()
            )

    def describe(self) -> str:
        with self._lock:
            parts = ", ".join(
                f"{m}(slots={h.engine.num_slots}, cap={h.engine.max_len}"
                f"{', draining' if h.draining else ''})"
                for m, h in self._hosted.items()
            )
        return f"{self.name}[{parts}]"
