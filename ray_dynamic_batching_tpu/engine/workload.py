"""Workload generation — rate patterns + request drivers for tests/benches.

Re-creates the reference's load generators: the in-process
``WorkloadGenerator`` patterns — linear slope
(``293-project/src/test_scheduler.py:77-96``), sinusoidal / step / random /
spike (``293-project/src/venkat-code/test_scheduler.py:110-126``) — and the
zmq request simulator's per-model threads pushing at a settable rate
(``293-project/src/milind-code/request_simulator.py:29-42``).

Additions for the TPU framework's north star: Poisson arrivals (BASELINE.md
headline metric is latency vs offered QPS under Poisson load) and a
deterministic virtual-clock mode so integration tests can assert SLO
outcomes without wall-clock flakiness.
"""

from __future__ import annotations

import json
import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional

from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("workload")


@dataclass
class RatePattern:
    """rate(t) in requests/sec over elapsed seconds ``t``."""

    kind: str = "constant"      # constant|linear|sinusoidal|step|random|spike
    base_rps: float = 10.0
    # linear: rate = base + slope * t  (ref test_scheduler.py:77-90)
    slope: float = 0.0
    # sinusoidal: base + amplitude * sin(2*pi*t/period)  (ref venkat :110-115)
    amplitude: float = 0.0
    period_s: float = 60.0
    # step: jumps to base+amplitude after step_at_s  (ref venkat :116-119)
    step_at_s: float = 30.0
    # random walk bounds  (ref venkat :120-122)
    jitter: float = 0.2
    # spike: base except [spike_at_s, spike_at_s+spike_len_s) at base+amplitude
    spike_at_s: float = 30.0
    spike_len_s: float = 5.0
    seed: int = 0
    _rng: random.Random = field(default_factory=random.Random, repr=False)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def rate(self, t: float) -> float:
        k = self.kind
        if k == "constant":
            r = self.base_rps
        elif k == "linear":
            r = self.base_rps + self.slope * t
        elif k == "sinusoidal":
            r = self.base_rps + self.amplitude * math.sin(
                2 * math.pi * t / self.period_s
            )
        elif k == "step":
            r = self.base_rps + (self.amplitude if t >= self.step_at_s else 0.0)
        elif k == "random":
            r = self.base_rps * (1 + self._rng.uniform(-self.jitter, self.jitter))
        elif k == "spike":
            in_spike = self.spike_at_s <= t < self.spike_at_s + self.spike_len_s
            r = self.base_rps + (self.amplitude if in_spike else 0.0)
        else:
            raise ValueError(f"unknown pattern kind {k!r}")
        return max(0.0, r)


def arrival_times(
    pattern: RatePattern,
    duration_s: float,
    poisson: bool = False,
    seed: int = 0,
) -> Iterator[float]:
    """Yield arrival offsets in [0, duration): deterministic uniform spacing
    at the instantaneous rate, or exponential gaps for Poisson arrivals."""
    rng = random.Random(seed)
    t = 0.0
    while t < duration_s:
        r = pattern.rate(t)
        if r <= 0:
            t += 0.05  # idle scan
            continue
        gap = rng.expovariate(r) if poisson else 1.0 / r
        t += gap
        if t < duration_s:
            yield t


class WorkloadDriver:
    """Threaded driver: submits via callback at pattern-scheduled times
    (one thread per model, ref request_simulator.py:29-42).

    ``record_path`` appends one JSONL line ``{"t_s": offset, "model":
    name}`` per submitted arrival — the replay format the what-if
    simulator consumes (``sim/workload.load_recorded_arrivals``), so any
    driven run becomes a reproducible simulation input. Drivers sharing
    one path append line-buffered (each line lands whole); the CALLER
    truncates the file once before starting its drivers.
    """

    def __init__(
        self,
        submit: Callable[[str, float], None],  # (model, arrival_offset_s)
        model: str,
        pattern: RatePattern,
        duration_s: float,
        poisson: bool = False,
        seed: int = 0,
        record_path: Optional[str] = None,
    ) -> None:
        self.submit = submit
        self.model = model
        self.pattern = pattern
        self.duration_s = duration_s
        self.poisson = poisson
        self.seed = seed
        self.record_path = record_path
        self.sent = 0
        self._thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        record = None
        if self.record_path:
            try:
                record = open(self.record_path, "a", buffering=1)
            except OSError:
                # Recording is a side feature: an unwritable path must
                # not kill the load-generation thread before it drives.
                logger.exception(
                    "cannot record arrivals to %s; driving unrecorded",
                    self.record_path,
                )
        start = time.monotonic()
        try:
            for offset in arrival_times(
                self.pattern, self.duration_s, self.poisson, self.seed
            ):
                delay = start + offset - time.monotonic()
                if delay > 0:
                    time.sleep(delay)  # rdb-lint: disable=event-loop-blocking (open-loop arrival pacing on the generator's own thread)
                if record is not None:
                    # Record BEFORE submitting: the trace is OFFERED
                    # load, and a replay must see arrivals the live run
                    # failed to deliver (else the recording inherits the
                    # survivor bias span replays are warned about).
                    try:
                        record.write(json.dumps(
                            {"t_s": round(offset, 6), "model": self.model}
                        ) + "\n")
                    except OSError:
                        # Disk trouble mid-run: a truncated record is not
                        # replayable — stop recording, keep driving, and
                        # say which it was (not a submit failure).
                        logger.exception(
                            "arrival recording to %s failed; recording "
                            "stopped, load generation continues",
                            self.record_path,
                        )
                        record.close()
                        record = None
                try:
                    self.submit(self.model, offset)
                    self.sent += 1
                except Exception:  # noqa: BLE001 — keep driving through errors
                    logger.exception(
                        "workload submit failed for %s", self.model
                    )
        finally:
            if record is not None:
                record.close()

    def start(self) -> "WorkloadDriver":
        self._thread = threading.Thread(
            target=self._run, name=f"workload-{self.model}", daemon=True
        )
        self._thread.start()
        return self

    def join(self, timeout_s: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout_s)


def run_workloads(drivers: List[WorkloadDriver], timeout_s: float) -> int:
    """Start all drivers, wait for completion; returns total sent."""
    for d in drivers:
        d.start()
    deadline = time.monotonic() + timeout_s
    for d in drivers:
        d.join(max(0.0, deadline - time.monotonic()))
    return sum(d.sent for d in drivers)
