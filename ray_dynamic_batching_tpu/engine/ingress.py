"""Socket ingress — line-delimited JSON over TCP into the scheduler queues.

Re-creates the reference's standalone zmq frontend
(``293-project/src/milind-code/scheduler.py:20-100``: PULL socket bound to
``tcp://*:5555`` at ``:33``, JSON requests ``{timestamp, model_name,
request_id, SLO, image_path}`` decoded and pushed to per-model Ray queues,
with per-second arrival-rate accounting ``:51-58``).

TPU-native differences: plain TCP with newline-delimited JSON (no zmq
dependency — we own both ends), the payload carries the model input inline
(tokens/features) instead of an image path, and — unlike the reference's
fire-and-forget pull — the server can stream each request's result back on
the same connection (``"reply": false`` restores the reference behavior).
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Any, Callable, Optional

from ray_dynamic_batching_tpu.engine.request import Request
from ray_dynamic_batching_tpu.utils.chaos import chaos
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("ingress")

DEFAULT_SLO_MS = 1000.0


class _IngressHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server: SocketIngress = self.server  # type: ignore[assignment]
        for raw in self.rfile:
            line = raw.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                model = msg["model_name"]
                request = Request(
                    model=model,
                    payload=msg.get("payload"),
                    slo_ms=float(msg.get("SLO", DEFAULT_SLO_MS)),
                    request_id=str(msg.get("request_id", "")),
                )
            except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
                self._reply({"error": f"bad request: {e}"})
                continue
            if chaos().should_fail("ingress.handle"):
                # chaos: ingress drops the request on the floor (lost
                # frontend RPC); client sees an error reply, not a hang
                self._reply(
                    {"request_id": request.request_id,
                     "error": "chaos injected at ingress.handle"}
                )
                continue
            accepted = server.submit(request)
            if not msg.get("reply", True):
                continue  # fire-and-forget (the reference's mode)
            if not accepted:
                self._reply(
                    {"request_id": request.request_id, "error": "rejected"}
                )
                continue
            try:
                result = request.future.result(timeout=server.reply_timeout_s)
                self._reply(
                    {"request_id": request.request_id,
                     "result": _jsonable(result)}
                )
            except Exception as e:  # noqa: BLE001 — deliver errors to the client
                self._reply(
                    {"request_id": request.request_id, "error": str(e)}
                )

    def _reply(self, obj: Any) -> None:
        try:
            self.wfile.write(json.dumps(obj).encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError):
            pass


def _jsonable(result: Any) -> Any:
    # One JSON-safety convention for both ingresses (dicts, np scalars and
    # arrays, dataclass-ish results all covered).
    from ray_dynamic_batching_tpu.serve.proxy import _to_jsonable

    return _to_jsonable(result)


class SocketIngress(socketserver.ThreadingTCPServer):
    """TCP ingress feeding a submit callback (``LiveScheduler.submit_request``
    or a router assign) — the RequestHandle role (ref :74-100)."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        submit: Callable[[Request], bool],
        host: str = "127.0.0.1",
        port: int = 5555,
        reply_timeout_s: float = 60.0,
    ) -> None:
        super().__init__((host, port), _IngressHandler)
        self.submit = submit
        self.reply_timeout_s = reply_timeout_s
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> "SocketIngress":
        self._thread = threading.Thread(
            target=self.serve_forever, name="socket-ingress", daemon=True
        )
        self._thread.start()
        logger.info("socket ingress on %s:%d", *self.server_address)
        return self

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class IngressClient:
    """Line-JSON client (the request-simulator side, ref
    request_simulator.py:33-42)."""

    def __init__(self, host: str, port: int, timeout_s: float = 60.0) -> None:
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self._file = self.sock.makefile("rwb")

    def send(
        self,
        model_name: str,
        payload: Any,
        slo_ms: float = DEFAULT_SLO_MS,
        request_id: str = "",
        reply: bool = True,
    ) -> Optional[dict]:
        msg = {
            "model_name": model_name,
            "payload": payload,
            "SLO": slo_ms,
            "request_id": request_id,
            "reply": reply,
        }
        self._file.write(json.dumps(msg).encode() + b"\n")
        self._file.flush()
        if not reply:
            return None
        line = self._file.readline()
        if not line:
            raise ConnectionError("ingress closed connection")
        return json.loads(line)

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self.sock.close()
