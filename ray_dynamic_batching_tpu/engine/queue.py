"""Per-model request queues with staleness discard and SLO accounting.

Re-creates the reference's ``RequestQueue``
(``293-project/src/scheduler.py:190-372``): bounded add with drop-when-full
(:238-254), batch pop that discards requests which can no longer meet their
deadline given the profiled batch latency (:281-283), per-request SLO-violation
accounting on completion (:324-341), rolling latency percentiles (:343-372).

TPU-native differences:
- batch pop is a single locked operation (the reference pops item-by-item over
  an actor RPC per element — its own noted inefficiency, scheduler.py:277);
- the queue is in-process and thread-safe (engine hot loops are threads; the
  asyncio ingress talks to it through request futures), with an optional
  native C++ ring planned behind the same interface.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional

from ray_dynamic_batching_tpu.engine.request import (
    Request,
    RequestDropped,
    RequestStale,
    now_ms,
)
from ray_dynamic_batching_tpu.utils.metrics import RollingWindow
from ray_dynamic_batching_tpu.utils.tracing import tracer

SLO_WINDOW = 200  # completions tracked for compliance stats (ref :324)


class RequestQueue:
    """Bounded FIFO for one model."""

    def __init__(self, model: str, max_len: int = 4096):
        self.model = model
        self.max_len = max_len
        self._q: Deque[Request] = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        # --- stats (ref :324-372) ---
        self.latency_window = RollingWindow(1000)
        self.queue_delay_window = RollingWindow(1000)
        self._recent_outcomes: Deque[bool] = deque(maxlen=SLO_WINDOW)
        self.total_enqueued = 0
        self.total_dropped = 0
        self.total_stale = 0
        self.total_completed = 0
        self.total_violations = 0

    # --- producer side ----------------------------------------------------
    def add_request(self, request: Request, reject_on_full: bool = True) -> bool:
        """Enqueue; when full, drop — rejecting the future (ref :238-254)
        unless ``reject_on_full=False`` (router retry path: a failed assign
        must stay retryable on another replica, not poison the future)."""
        with self._lock:
            if self._closed or len(self._q) >= self.max_len:
                if reject_on_full:
                    # Retryable declines (reject_on_full=False) are not
                    # drops — another replica may serve the request.
                    self.total_dropped += 1
                    request.reject(
                        RequestDropped(
                            f"{self.model}: "
                            + ("closed" if self._closed
                               else f"queue full ({self.max_len})")
                        )
                    )
                return False
            request.enqueue_ms = now_ms()
            self._q.append(request)
            self.total_enqueued += 1
            self._not_empty.notify()
            return True

    # --- consumer side ----------------------------------------------------
    def get_batch(
        self,
        batch_size: int,
        expected_latency_ms: float = 0.0,
        discard_stale: bool = True,
    ) -> List[Request]:
        """Pop up to ``batch_size`` requests in one locked sweep, discarding
        any that cannot finish inside their SLO even if run right now
        (arrival + slo < now + expected_latency — ref :281-283)."""
        now = now_ms()
        out: List[Request] = []
        stale: List[Request] = []
        with self._lock:
            while self._q and len(out) < batch_size:
                req = self._q.popleft()
                if (
                    discard_stale
                    and req.deadline_ms < now + expected_latency_ms
                ):
                    stale.append(req)
                    continue
                out.append(req)
            self.total_stale += len(stale)
            depth_after = len(self._q)
        for req in stale:
            req.reject(
                RequestStale(
                    f"{req.request_id}: deadline missed before execution"
                )
            )
        if out and tracer().enabled:
            # Retroactive queue-wait span per popped request: enqueue ->
            # this pop, joined to the request's trace (the recorder's
            # "where did the milliseconds go" hop between routing and
            # batch execution).
            pop_ms = now_ms()
            for req in out:
                tracer().record_span(
                    "queue.wait",
                    ctx=req.trace_ctx,
                    start_ms=req.enqueue_ms or req.arrival_ms,
                    end_ms=pop_ms,
                    model=self.model,
                    lane=self.model,
                    depth_after=depth_after,
                )
        return out

    def wait_for_requests(self, timeout_s: float) -> bool:
        """Block until the queue is non-empty (engine idle wait)."""
        with self._lock:
            if self._q:
                return True
            if self._closed:
                return False
            return self._not_empty.wait(timeout_s)

    def wait_for_batch(
        self,
        batch_size: int,
        wait_timeout_s: float,
        idle_wait_s: float = 0.5,
    ) -> None:
        """Block until ``batch_size`` requests are queued OR
        ``wait_timeout_s`` has elapsed since the FIRST queued request arrived
        (Serve's size-or-timeout discipline, ref serve/batching.py:146-197).
        Condition-variable based: no polling, woken by add_request. An EMPTY
        queue blocks up to ``idle_wait_s`` per arm — the batch timeout only
        gates a partially-filled batch, so idle consumers don't spin at the
        (possibly sub-ms) batch cadence. Returns immediately once closed."""
        import time as _time

        with self._lock:
            while not self._closed:
                if len(self._q) >= batch_size:
                    return
                if self._q:
                    deadline_s = (
                        self._q[0].arrival_ms / 1000.0 + wait_timeout_s
                    )
                    remaining = deadline_s - _time.monotonic()
                    if remaining <= 0:
                        return
                    self._not_empty.wait(remaining)
                else:
                    if not self._not_empty.wait(max(idle_wait_s, wait_timeout_s)):
                        return  # stayed empty for a full idle window

    def wake_waiters(self) -> None:
        """Wake any consumer blocked in wait_for_batch/wait_for_requests
        (they re-arm if the queue is still open and empty)."""
        with self._lock:
            self._not_empty.notify_all()

    def close(self) -> None:
        """Unblock and permanently release all waiters; new adds are
        declined (shutdown path — a closed queue never blocks a consumer)."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    def peek_arrival_ms(self) -> Optional[float]:
        with self._lock:
            return self._q[0].arrival_ms if self._q else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    # --- accounting (ref record_batch_completion, :324-341) ---------------
    def record_batch_completion(
        self, batch: List[Request], completed_at_ms: Optional[float] = None
    ) -> int:
        """Count per-request SLO outcomes against arrival time; returns the
        number of violations in this batch."""
        t = completed_at_ms if completed_at_ms is not None else now_ms()
        violations = 0
        for req in batch:
            total_ms = t - req.arrival_ms
            ok = total_ms <= req.slo_ms
            violations += 0 if ok else 1
            self.latency_window.observe(total_ms)
            self.queue_delay_window.observe(req.queue_delay_ms(t))
            self._recent_outcomes.append(ok)
        self.total_completed += len(batch)
        self.total_violations += violations
        return violations

    def slo_compliance(self) -> float:
        """Fraction of recent completions inside SLO (1.0 when idle)."""
        if not self._recent_outcomes:
            return 1.0
        return sum(self._recent_outcomes) / len(self._recent_outcomes)

    def stats(self) -> Dict[str, float]:
        return {
            "depth": float(len(self)),
            "enqueued": float(self.total_enqueued),
            "dropped": float(self.total_dropped),
            "stale": float(self.total_stale),
            "completed": float(self.total_completed),
            "violations": float(self.total_violations),
            "slo_compliance": self.slo_compliance(),
            "latency_p50_ms": self.latency_window.percentile(0.50),
            "latency_p95_ms": self.latency_window.percentile(0.95),
            "latency_p99_ms": self.latency_window.percentile(0.99),
            "queue_delay_p95_ms": self.queue_delay_window.percentile(0.95),
        }


class QueueManager:
    """Name → queue registry shared by ingress, engines, and control loop."""

    def __init__(self, max_len: int = 4096):
        self.max_len = max_len
        self._queues: Dict[str, RequestQueue] = {}
        self._lock = threading.Lock()

    def queue(self, model: str) -> RequestQueue:
        with self._lock:
            if model not in self._queues:
                self._queues[model] = RequestQueue(model, self.max_len)
            return self._queues[model]

    def queues(self) -> Dict[str, RequestQueue]:
        with self._lock:
            return dict(self._queues)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {m: q.stats() for m, q in self.queues().items()}
