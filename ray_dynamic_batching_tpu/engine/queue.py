"""Per-model request queues with staleness discard and SLO accounting.

Re-creates the reference's ``RequestQueue``
(``293-project/src/scheduler.py:190-372``): bounded add with drop-when-full
(:238-254), batch pop that discards requests which can no longer meet their
deadline given the profiled batch latency (:281-283), per-request SLO-violation
accounting on completion (:324-341), rolling latency percentiles (:343-372).

TPU-native differences:
- batch pop is a single locked operation (the reference pops item-by-item over
  an actor RPC per element — its own noted inefficiency, scheduler.py:277);
- the queue is in-process and thread-safe (engine hot loops are threads; the
  asyncio ingress talks to it through request futures), with an optional
  native C++ ring planned behind the same interface.

Multi-tenant QoS (Shepherd-style, ROADMAP item 4): ordering is **class then
deadline** — ``interactive`` dequeues before ``standard`` before
``best_effort``, and within a class the earliest deadline wins. Overflow
sheds **best-effort first**: a full queue evicts the latest-deadline request
of the lowest-priority class present rather than dropping a higher-class
arrival. A pinned anti-starvation stride bounds priority inversion the other
way: after :data:`ANTI_STARVATION_STRIDE` consecutive pops that bypassed
queued lower-priority work, one pop serves the longest-waiting lower class —
so best-effort always eventually drains when capacity exists. The ordering
core (:class:`ClassBuckets`) is pure and shared verbatim by the simulator's
queue (``sim/queue.py``) so the two sides cannot drift.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Dict, List, Optional

from ray_dynamic_batching_tpu.engine.request import (
    QOS_RANK,
    Request,
    RequestDropped,
    RequestStale,
    now_ms,
)
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils.sketch import RollingSketch
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import tracer

SLO_WINDOW = 200  # completions tracked for compliance stats (ref :324)

# After this many consecutive pops that served a class while lower-priority
# work waited, ONE pop goes to the longest-waiting lower class. Pinned: it
# is the anti-starvation contract (best-effort gets >= 1/(STRIDE+1) of pops
# whenever it is backlogged), asserted by tests/test_qos.py.
ANTI_STARVATION_STRIDE = 8

SHED_TOTAL = m.Counter(
    "rdb_shed_total",
    "Requests shed by a queue (reason: full | displaced | stale | closed "
    "| requeue_refused | cancelled)",
    tag_keys=("model", "qos", "reason"),
)


class ClassBuckets:
    """Pure class-then-deadline ordering over items exposing ``qos_class``,
    ``deadline_ms`` and ``arrival_ms`` (live :class:`Request` and the sim's
    ``SimRequest`` both do). NOT thread-safe — the owning queue locks.

    Every structure is a heap with LAZY deletion (per-heap tombstone
    sets): pops take the min-deadline entry, sheds take the MAX-deadline
    entry of the lowest class via a reversed side-heap, and the batching
    timeout reads the min arrival via a third — all amortized O(log n).
    An eager removal would pay an O(n) scan + heapify under the queue
    lock per full-queue arrival, exactly in the sustained-overload regime
    this layer exists for."""

    def __init__(self) -> None:
        # qos -> [(deadline_ms, seq, item)]; seq breaks ties so items are
        # never compared and equal deadlines stay FIFO.
        self._heaps: Dict[str, list] = {}
        # qos -> [(-deadline_ms, -seq, item)]: the shed side (latest
        # deadline first; -seq so equal deadlines shed the NEWEST).
        self._rev_heaps: Dict[str, list] = {}
        self._live: Dict[str, int] = {}   # per-class live entry count
        self._arrival_heap: list = []     # [(arrival_ms, seq)]
        self._seq = itertools.count()
        self._size = 0
        self._skips = 0  # consecutive pops that bypassed lower-priority work
        # seq -> removed, one tombstone set per heap family (an entry
        # appears once in each, so discard-on-purge is safe per set).
        self._gone_fwd: set = set()
        self._gone_rev: set = set()
        self._gone_arr: set = set()

    def __len__(self) -> int:
        return self._size

    def push(self, item) -> None:
        self._maybe_compact()
        cls = item.qos_class
        seq = next(self._seq)
        heapq.heappush(self._heaps.setdefault(cls, []),
                       (item.deadline_ms, seq, item))
        heapq.heappush(self._rev_heaps.setdefault(cls, []),
                       (-item.deadline_ms, -seq, item))
        heapq.heappush(self._arrival_heap, (item.arrival_ms, seq))
        self._live[cls] = self._live.get(cls, 0) + 1
        self._size += 1

    def _maybe_compact(self) -> None:
        """Rebuild every heap from live entries once tombstones outnumber
        them. Lazy deletion only drains tombstoned HEADS as they surface;
        a healthy never-full queue pops from the fwd side forever while
        its rev/arrival entries (and their tombstones) accrete — without
        this, one dead tuple + seq per served request is retained for the
        process lifetime. O(n) rebuild amortized over >= n removals."""
        tombs = (len(self._gone_fwd) + len(self._gone_rev)
                 + len(self._gone_arr))
        if tombs <= max(64, 2 * self._size):
            return
        live = [
            entry
            for heap in self._heaps.values()
            for entry in heap
            if entry[1] not in self._gone_fwd
        ]
        self._heaps = {}
        self._rev_heaps = {}
        arrival = []
        for deadline, seq, item in live:
            self._heaps.setdefault(item.qos_class, []).append(
                (deadline, seq, item)
            )
            self._rev_heaps.setdefault(item.qos_class, []).append(
                (-deadline, -seq, item)
            )
            arrival.append((item.arrival_ms, seq))
        for heap in self._heaps.values():
            heapq.heapify(heap)
        for heap in self._rev_heaps.values():
            heapq.heapify(heap)
        heapq.heapify(arrival)
        self._arrival_heap = arrival
        self._gone_fwd = set()
        self._gone_rev = set()
        self._gone_arr = set()

    def _purge(self, heap: list, gone: set, seq_of) -> None:
        while heap and seq_of(heap[0]) in gone:
            gone.discard(seq_of(heapq.heappop(heap)))

    def _fwd_head(self, cls: str):
        heap = self._heaps[cls]
        self._purge(heap, self._gone_fwd, lambda e: e[1])
        return heap[0]

    def _present(self) -> List[str]:
        """Classes with live entries, highest priority (lowest rank)
        first. Unknown classes rank beyond last — lowest priority on
        BOTH the dequeue and the shed side (see :meth:`shed_victim`)."""
        return sorted(
            (c for c, n in self._live.items() if n > 0),
            key=lambda c: QOS_RANK.get(c, len(QOS_RANK)),
        )

    def pop(self):
        """Next item: highest-priority class, earliest deadline — except
        that every :data:`ANTI_STARVATION_STRIDE`-th bypass serves the
        lower-priority class whose head has waited longest (pinned
        anti-starvation bound)."""
        present = self._present()
        if not present:
            return None
        if len(present) == 1:
            self._skips = 0
            cls = present[0]
        elif self._skips >= ANTI_STARVATION_STRIDE:
            self._skips = 0
            cls = min(
                present[1:],
                key=lambda c: self._fwd_head(c)[2].arrival_ms,
            )
        else:
            self._skips += 1
            cls = present[0]
        self._fwd_head(cls)  # ensure a live head
        _deadline, seq, item = heapq.heappop(self._heaps[cls])
        self._gone_rev.add(seq)
        self._gone_arr.add(seq)
        self._live[cls] -= 1
        self._size -= 1
        return item

    def shed_victim(self, incoming):
        """The queued item to evict so ``incoming`` fits, or None when the
        incoming request IS the shed victim (nothing lower-priority is
        queued — equal class drops the newcomer, the pre-QoS behavior)."""
        present = self._present()
        if not present:
            return None
        lowest = present[-1]
        worst_rank = len(QOS_RANK)
        if QOS_RANK.get(lowest, worst_rank) <= QOS_RANK.get(
            incoming.qos_class, worst_rank
        ):
            return None
        # Latest deadline = least urgent work of the least important class.
        heap = self._rev_heaps[lowest]
        self._purge(heap, self._gone_rev, lambda e: -e[1])
        _negdl, negseq, victim = heapq.heappop(heap)
        self._gone_fwd.add(-negseq)
        self._gone_arr.add(-negseq)
        self._live[lowest] -= 1
        self._size -= 1
        return victim

    def earliest_arrival_ms(self) -> Optional[float]:
        self._purge(self._arrival_heap, self._gone_arr, lambda e: e[1])
        return self._arrival_heap[0][0] if self._arrival_heap else None

    def depth_by_class(self) -> Dict[str, int]:
        return {c: n for c, n in self._live.items() if n > 0}


class ClassCounters:
    """Per-class slices of the queue counters — ONE implementation shared
    by the live and sim queues (same no-drift discipline as
    :class:`ClassBuckets`): per-class "enqueued" counts every request
    OFFERED at the door (door-drops included), so conservation holds
    unconditionally: enqueued == completed + stale + dropped + depth.
    Lock-free; the owning queue serializes access."""

    KEYS = ("enqueued", "dropped", "stale", "completed", "violations")

    def __init__(self) -> None:
        self._counters: Dict[str, Dict[str, float]] = {}

    def cls(self, qos: str) -> Dict[str, float]:
        c = self._counters.get(qos)
        if c is None:
            c = self._counters[qos] = {k: 0.0 for k in self.KEYS}
        return c

    def stats(self, depths: Dict[str, int]) -> Dict[str, Dict[str, float]]:
        """Counter slices + live depth per class (sorted for
        deterministic report rendering)."""
        out = {}
        for cls in sorted(set(self._counters) | set(depths)):
            c = dict(self._counters.get(cls, {k: 0.0 for k in self.KEYS}))
            c["depth"] = float(depths.get(cls, 0))
            out[cls] = c
        return out


class RequestQueue:
    """Bounded class-then-deadline queue for one model."""

    def __init__(self, model: str, max_len: int = 4096):
        self.model = model
        self.max_len = max_len
        self._buckets = ClassBuckets()
        self._lock = OrderedLock("request_queue")
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        # Optional decision ring (scheduler/audit.AuditLog): class-aware
        # displacement sheds are control-plane-visible decisions; the
        # router/controller wires its ring here (None = unaudited).
        self.audit = None
        # --- stats (ref :324-372) ---
        # Rolling quantile SKETCHES (PR 8): the compliance signals the
        # router/failover/governor read (`_retry_hint_s`, `stats()`
        # percentiles) hold a guaranteed relative error (default 1%)
        # and read in O(bins) instead of an O(n log n) sort under the
        # queue lock per stats() call. RECENCY is preserved: epochs
        # rotate every 1000 observations, so a read reflects at most
        # the last ~2000 completions — a retry hint must describe the
        # queue NOW, not a whole healthy morning. Same observe/
        # percentile surface as the deprecated RollingWindow(1000).
        self.latency_window = RollingSketch(1000)
        self.queue_delay_window = RollingSketch(1000)
        # Service-time slice of the same completions (total minus queue
        # delay): the live "engine.step" hop the SLO observatory grades
        # against the cost model's profile-row prediction — same hop
        # name, same sketch type as the sim's virtual-event ledger.
        self.service_window = RollingSketch(1000)
        self._recent_outcomes = []
        self.total_enqueued = 0
        self.total_dropped = 0
        self.total_stale = 0
        self.total_completed = 0
        self.total_violations = 0
        # Live-migration accounting (page fabric): a stream moved to a
        # peer engine leaves this queue's books through migrated_out
        # (neither completed nor dropped — it finishes elsewhere) and
        # enters the destination's through migrated_in (counted as
        # offered-at-door enqueued there). Conservation extends to
        # ``enqueued == completed + stale + dropped + migrated_out +
        # depth`` — trivially the old identity while both stay zero.
        self.total_migrated_out = 0
        self.total_migrated_in = 0
        # Per-class slices of the same counters (ClassCounters docstring
        # has the offered-at-door conservation contract).
        self._classes = ClassCounters()

    def _cls(self, qos: str) -> Dict[str, float]:
        return self._classes.cls(qos)

    def _retry_hint_s(self) -> float:
        """Computed ``Retry-After`` for capacity rejects: the recent p50
        request latency is the expected time for a queue slot to free —
        a client that waits it out meets a drained-a-little queue. 1 s
        before any completion (no data beats a wrong hint)."""
        p50_ms = self.latency_window.percentile(0.5)
        return max(0.05, p50_ms / 1000.0) if p50_ms > 0 else 1.0

    def _audit_shed(self, victim: Request, incoming: Request) -> None:
        if self.audit is not None:
            self.audit.record(
                "qos_shed",
                key=self.model,
                observed={"victim": victim.request_id,
                          "victim_qos": victim.qos_class,
                          "victim_tenant": victim.tenant,
                          "for_qos": incoming.qos_class},
                diff={"displaced": victim.qos_class},
                note="full queue: lowest-class latest-deadline displaced",
            )

    # --- producer side ----------------------------------------------------
    def add_request(self, request: Request, reject_on_full: bool = True,
                    requeue: bool = False) -> bool:
        """Enqueue; when full, shed the lowest-priority latest-deadline
        queued request to make room (class-aware shed), or — when nothing
        queued is lower-priority than the arrival — drop the arrival
        itself, rejecting the future (ref :238-254) unless
        ``reject_on_full=False`` (router retry path: a failed assign must
        stay retryable on another replica, not poison the future).
        ``requeue=True`` marks work RETURNING to the queue (chunked
        admission handing back a popped request): it must not count as a
        fresh offer or per-class conservation over-counts ``enqueued``."""
        victim: Optional[Request] = None
        with self._lock:
            if self._closed:
                if reject_on_full:
                    self.total_dropped += 1
                    c = self._cls(request.qos_class)
                    c["enqueued"] += 1  # offered-at-door (conservation)
                    c["dropped"] += 1
                    SHED_TOTAL.inc(tags={"model": self.model,
                                         "qos": request.qos_class,
                                         "reason": "closed"})
                    request.reject(
                        RequestDropped(f"{self.model}: closed")
                    )
                return False
            if len(self._buckets) >= self.max_len:
                victim = self._buckets.shed_victim(request)
                if victim is None:
                    if reject_on_full:
                        # Retryable declines (reject_on_full=False) are not
                        # drops — another replica may serve the request.
                        self.total_dropped += 1
                        c = self._cls(request.qos_class)
                        c["enqueued"] += 1  # offered-at-door
                        c["dropped"] += 1
                        SHED_TOTAL.inc(tags={"model": self.model,
                                             "qos": request.qos_class,
                                             "reason": "full"})
                        exc = RequestDropped(
                            f"{self.model}: queue full ({self.max_len})"
                        )
                        exc.retry_after_s = self._retry_hint_s()
                        request.reject(exc)
                    return False
                self.total_dropped += 1
                self._cls(victim.qos_class)["dropped"] += 1
                SHED_TOTAL.inc(tags={"model": self.model,
                                     "qos": victim.qos_class,
                                     "reason": "displaced"})
            request.enqueue_ms = now_ms()
            self._buckets.push(request)
            if not requeue:
                self.total_enqueued += 1
                self._cls(request.qos_class)["enqueued"] += 1
            self._not_empty.notify()
        if victim is not None:
            self._audit_shed(victim, request)
            exc = RequestDropped(
                f"{self.model}: displaced by {request.qos_class} "
                f"arrival (queue full, {victim.qos_class} sheds first)"
            )
            exc.retry_after_s = self._retry_hint_s()
            victim.reject(exc)
        return True

    # --- consumer side ----------------------------------------------------
    def get_batch(
        self,
        batch_size: int,
        expected_latency_ms: float = 0.0,
        discard_stale: bool = True,
    ) -> List[Request]:
        """Pop up to ``batch_size`` requests in one locked sweep — class
        then deadline, anti-starvation stride applied — discarding any
        that cannot finish inside their SLO even if run right now
        (arrival + slo < now + expected_latency — ref :281-283)."""
        now = now_ms()
        out: List[Request] = []
        stale: List[Request] = []
        cancelled: List[Request] = []
        with self._lock:
            while len(self._buckets) and len(out) < batch_size:
                req = self._buckets.pop()
                if getattr(req, "cancelled", False):
                    # Hedge-race loser: its outcome was already delivered
                    # by the winning dispatch. Free the slot and account
                    # it EXACTLY once (dropped/cancelled) so enqueued ==
                    # completed + stale + dropped + depth conserves; the
                    # future is already resolved, so no reject.
                    cancelled.append(req)
                    self.total_dropped += 1
                    self._cls(req.qos_class)["dropped"] += 1
                    continue
                if (
                    discard_stale
                    and req.deadline_ms < now + expected_latency_ms
                ):
                    stale.append(req)
                    self._cls(req.qos_class)["stale"] += 1
                    continue
                out.append(req)
            self.total_stale += len(stale)
            depth_after = len(self._buckets)
        for req in cancelled:
            SHED_TOTAL.inc(tags={"model": self.model,
                                 "qos": req.qos_class,
                                 "reason": "cancelled"})
        for req in stale:
            SHED_TOTAL.inc(tags={"model": self.model,
                                 "qos": req.qos_class, "reason": "stale"})
            exc = RequestStale(
                f"{req.request_id}: deadline missed before execution"
            )
            exc.retry_after_s = self._retry_hint_s()
            req.reject(exc)
        if out and tracer().enabled:
            # Retroactive queue-wait span per popped request: enqueue ->
            # this pop, joined to the request's trace (the recorder's
            # "where did the milliseconds go" hop between routing and
            # batch execution). Tenant/class ride the span so overload
            # triage can slice wait time by service tier.
            pop_ms = now_ms()
            for req in out:
                tracer().record_span(
                    "queue.wait",
                    ctx=req.trace_ctx,
                    start_ms=req.enqueue_ms or req.arrival_ms,
                    end_ms=pop_ms,
                    model=self.model,
                    lane=self.model,
                    depth_after=depth_after,
                    tenant=req.tenant,
                    qos_class=req.qos_class,
                )
        return out

    def wait_for_requests(self, timeout_s: float) -> bool:
        """Block until the queue is non-empty (engine idle wait)."""
        with self._lock:
            if len(self._buckets):
                return True
            if self._closed:
                return False
            return self._not_empty.wait(timeout_s)

    def wait_for_batch(
        self,
        batch_size: int,
        wait_timeout_s: float,
        idle_wait_s: float = 0.5,
    ) -> None:
        """Block until ``batch_size`` requests are queued OR
        ``wait_timeout_s`` has elapsed since the FIRST queued request arrived
        (Serve's size-or-timeout discipline, ref serve/batching.py:146-197).
        Condition-variable based: no polling, woken by add_request. An EMPTY
        queue blocks up to ``idle_wait_s`` per arm — the batch timeout only
        gates a partially-filled batch, so idle consumers don't spin at the
        (possibly sub-ms) batch cadence. Returns immediately once closed."""
        import time as _time

        with self._lock:
            while not self._closed:
                if len(self._buckets) >= batch_size:
                    return
                earliest = self._buckets.earliest_arrival_ms()
                if earliest is not None:
                    deadline_s = earliest / 1000.0 + wait_timeout_s
                    remaining = deadline_s - _time.monotonic()
                    if remaining <= 0:
                        return
                    self._not_empty.wait(remaining)
                else:
                    if not self._not_empty.wait(max(idle_wait_s, wait_timeout_s)):
                        return  # stayed empty for a full idle window

    def wake_waiters(self) -> None:
        """Wake any consumer blocked in wait_for_batch/wait_for_requests
        (they re-arm if the queue is still open and empty)."""
        with self._lock:
            self._not_empty.notify_all()

    def close(self) -> None:
        """Unblock and permanently release all waiters; new adds are
        declined (shutdown path — a closed queue never blocks a consumer)."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    def peek_arrival_ms(self) -> Optional[float]:
        with self._lock:
            return self._buckets.earliest_arrival_ms()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)

    # --- accounting (ref record_batch_completion, :324-341) ---------------
    def record_batch_completion(
        self, batch: List[Request], completed_at_ms: Optional[float] = None
    ) -> int:
        """Count per-request SLO outcomes against arrival time; returns the
        number of violations in this batch."""
        t = completed_at_ms if completed_at_ms is not None else now_ms()
        violations = 0
        with self._lock:
            for req in batch:
                total_ms = t - req.arrival_ms
                ok = total_ms <= req.slo_ms
                violations += 0 if ok else 1
                self.latency_window.observe(total_ms)
                delay_ms = req.queue_delay_ms(t)
                self.queue_delay_window.observe(delay_ms)
                self.service_window.observe(max(0.0, total_ms - delay_ms))
                self._recent_outcomes.append(ok)
                c = self._cls(req.qos_class)
                c["completed"] += 1
                c["violations"] += 0 if ok else 1
            if len(self._recent_outcomes) > SLO_WINDOW:
                del self._recent_outcomes[:-SLO_WINDOW]
            self.total_completed += len(batch)
            self.total_violations += violations
        return violations

    def count_external_drop(self, request: Request,
                            reason: str = "closed") -> None:
        """Account a drop decided OUTSIDE the queue (drain-and-stop and
        teardown paths): work popped by ``drain_queue`` and then rejected
        would otherwise vanish from ``enqueued == completed + stale +
        dropped + depth`` conservation."""
        with self._lock:
            self.total_dropped += 1
            self._cls(request.qos_class)["dropped"] += 1
        SHED_TOTAL.inc(tags={"model": self.model,
                             "qos": request.qos_class, "reason": reason})

    def note_migrated_out(self, request: Request) -> None:
        """Close this queue's books on a stream the page fabric moved to
        a peer engine: it was enqueued here but will complete THERE —
        neither a completion nor a drop (the client sees one unbroken
        stream). Called by the engine thread at migrate-out commit."""
        with self._lock:
            self.total_migrated_out += 1

    def note_migrated_in(self, request: Request) -> None:
        """Open this queue's books on a stream migrated in from a peer:
        counted as offered-at-door enqueued (same rule as every other
        arrival) so its eventual record_batch_completion balances."""
        with self._lock:
            self.total_enqueued += 1
            self.total_migrated_in += 1
            self._cls(request.qos_class)["enqueued"] += 1

    def slo_compliance(self) -> float:
        """Fraction of recent completions inside SLO (1.0 when idle)."""
        # Snapshot under the lock: an unlocked sum()/len() pair can
        # straddle complete_batch's trim and report > 1.0 (the sum sees
        # the pre-trim list, the len the post-trim one).
        with self._lock:
            outcomes = list(self._recent_outcomes)
        if not outcomes:
            return 1.0
        return sum(outcomes) / len(outcomes)

    def stats(self) -> Dict[str, float]:
        out = {
            "depth": float(len(self)),
            "enqueued": float(self.total_enqueued),
            "dropped": float(self.total_dropped),
            "stale": float(self.total_stale),
            "completed": float(self.total_completed),
            "violations": float(self.total_violations),
            "slo_compliance": self.slo_compliance(),
            "latency_p50_ms": self.latency_window.percentile(0.50),
            "latency_p95_ms": self.latency_window.percentile(0.95),
            "latency_p99_ms": self.latency_window.percentile(0.99),
            "queue_delay_p95_ms": self.queue_delay_window.percentile(0.95),
        }
        # Elided when zero so non-migrating deployments' stats payloads
        # stay byte-identical to pre-fabric builds.
        if self.total_migrated_out:
            out["migrated_out"] = float(self.total_migrated_out)
        if self.total_migrated_in:
            out["migrated_in"] = float(self.total_migrated_in)
        return out

    def class_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-class counter slices + live depth, for QoS accounting
        (same key set as the sim queue's — report code reads either)."""
        with self._lock:
            return self._classes.stats(self._buckets.depth_by_class())


class QueueManager:
    """Name → queue registry shared by ingress, engines, and control loop."""

    def __init__(self, max_len: int = 4096):
        self.max_len = max_len
        self._queues: Dict[str, RequestQueue] = {}
        self._lock = threading.Lock()

    def queue(self, model: str) -> RequestQueue:
        with self._lock:
            if model not in self._queues:
                self._queues[model] = RequestQueue(model, self.max_len)
            return self._queues[model]

    def queues(self) -> Dict[str, RequestQueue]:
        with self._lock:
            return dict(self._queues)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {m: q.stats() for m, q in self.queues().items()}
