"""Cross-process serving bridge over the C++ shm substrate.

The reference splits its data plane between gRPC (control/small data) and
plasma shared memory (large payloads) — SURVEY.md §2.2/§2.4. This module is
that pairing for the serving path, on the native substrate (`native/`):

- control plane: request *metadata* rides a :class:`NativeQueue` (shm MPMC
  ring) and is drained by the engine in ONE batch-pop per cycle — the
  single-RPC batch pop the reference's queue lacks (scheduler.py:277);
- data plane: request payloads and results ride the :class:`ObjectStore`
  (shm arena, plasma role), referenced by object id from the metadata.

Frontend processes (:class:`ShmFrontend`) submit and await results without
importing jax or touching the engine process; the engine side
(:class:`ShmBridge`) adapts popped requests into ordinary
:class:`engine.request.Request` objects whose completion writes the result
back into the store.
"""

from __future__ import annotations

import io
import json
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_dynamic_batching_tpu.engine.request import Request, now_ms
from ray_dynamic_batching_tpu.runtime.native import NativeQueue, ObjectStore
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("shm_bridge")

_RESULT_BIT = 1 << 63  # result object id = payload oid | result bit
_OID_MASK = _RESULT_BIT - 1


def _encode_value(value: Any) -> bytes:
    """np arrays as npy bytes (zero-ambiguity dtypes/shapes); everything
    else as json."""
    if isinstance(value, np.ndarray):
        buf = io.BytesIO()
        np.save(buf, value, allow_pickle=False)
        return b"NPY0" + buf.getvalue()
    return b"JSON" + json.dumps(value).encode()


def _decode_value(data: bytes) -> Any:
    tag, body = data[:4], data[4:]
    if tag == b"NPY0":
        return np.load(io.BytesIO(body), allow_pickle=False)
    if tag == b"JSON":
        return json.loads(body)
    raise ValueError(f"unknown payload tag {tag!r}")


class ShmFrontend:
    """Client-side handle in the frontend process: submit + await results."""

    def __init__(self, name: str, create: bool = False,
                 queue_capacity: int = 4096, store_bytes: int = 256 << 20):
        self.queue = NativeQueue(
            f"{name}.q", capacity=queue_capacity, item_size=4096, create=create
        )
        self.store = ObjectStore(
            f"{name}.store", capacity_bytes=store_bytes, create=create
        )

    def submit(self, model: str, payload: Any, slo_ms: float,
               request_id: Optional[str] = None) -> int:
        """Enqueue one request; returns the oid to poll for the result.
        Raises RuntimeError when the queue drops (backpressure is visible,
        never silent)."""
        request_id = request_id or uuid.uuid4().hex
        oid = uuid.uuid4().int & _OID_MASK
        if not self.store.put(oid, _encode_value(payload)):
            raise RuntimeError("shm store full: payload rejected")
        meta = json.dumps({
            "model": model,
            "slo_ms": slo_ms,
            "request_id": request_id,
            "oid": oid,
            # monotonic: shm is same-host, so CLOCK_MONOTONIC is shared
            # across processes and comparable with the engine's now_ms()
            "ts_ms": now_ms(),
        }).encode()
        try:
            pushed = self.queue.push(meta)
        except ValueError:
            self.store.delete(oid)  # oversized meta: reclaim the payload
            raise
        if not pushed:
            self.store.delete(oid)
            raise RuntimeError("shm queue full: request dropped")
        return oid

    def try_result(self, oid: int, delete: bool = True):
        """Non-blocking result probe: (False, None) when not ready yet,
        (True, value) when done; raises the engine-reported error. Lets a
        single poller thread multiplex many outstanding oids instead of one
        blocked ``get_result`` thread per request."""
        result_oid = oid | _RESULT_BIT
        data = self.store.get(result_oid)
        if data is None:
            return False, None
        if delete:
            self.store.delete(result_oid)
            self.store.delete(oid)
        value = _decode_value(data)
        if isinstance(value, dict) and "__error__" in value:
            raise RuntimeError(value["__error__"])
        return True, value

    def get_result(self, oid: int, timeout_s: float = 30.0,
                   poll_s: float = 0.002, delete: bool = True) -> Any:
        """Poll the store for the result object; raises on timeout or if
        the engine reported an error for this request."""
        result_oid = oid | _RESULT_BIT
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            data = self.store.get(result_oid)
            if data is not None:
                if delete:
                    self.store.delete(result_oid)
                    self.store.delete(oid)
                value = _decode_value(data)
                if isinstance(value, dict) and "__error__" in value:
                    raise RuntimeError(value["__error__"])
                return value
            time.sleep(poll_s)  # rdb-lint: disable=event-loop-blocking (cross-process shm result poll on the frontend caller's thread)
        raise TimeoutError(f"no result for oid {oid} within {timeout_s}s")

    def close(self, unlink: Optional[bool] = None) -> None:
        self.queue.close(unlink)
        self.store.close(unlink)


class ShmBridge:
    """Engine-side pump: batch-pops shm requests, rehydrates payloads from
    the store, and submits Requests whose completion writes results back."""

    def __init__(self, name: str, submit: Callable[[Request], bool],
                 batch_size: int = 64, create: bool = True,
                 queue_capacity: int = 4096, store_bytes: int = 256 << 20):
        self.frontend_name = name
        self.queue = NativeQueue(
            f"{name}.q", capacity=queue_capacity, item_size=4096, create=create
        )
        self.store = ObjectStore(
            f"{name}.store", capacity_bytes=store_bytes, create=create
        )
        self.submit = submit
        self.batch_size = batch_size
        self._run = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pumped = 0
        self.errors = 0
        self.result_drops = 0

    # --- result write-back -------------------------------------------------
    def _complete(self, oid: int, value: Any) -> None:
        try:
            ok = self.store.put(oid | _RESULT_BIT, _encode_value(value))
        except KeyError:
            return  # duplicate completion; first write wins (immutable store)
        if not ok:
            # result didn't fit the arena: the frontend will time out, so
            # make the reason findable (backpressure visible, never silent)
            self.result_drops += 1
            logger.error(
                "result for oid %d dropped: shm store full (%d bytes used)",
                oid, self.store.used_bytes,
            )

    def _make_request(self, meta: Dict[str, Any]) -> Optional[Request]:
        oid = meta["oid"]
        data = self.store.get(oid)
        if data is None:
            logger.warning("payload oid %d missing (evicted?)", oid)
            self._complete(oid, {"__error__": "payload missing from store"})
            return None
        try:
            payload = _decode_value(data)
        except Exception as e:  # noqa: BLE001 — report to the waiting frontend
            logger.warning("payload oid %d undecodable: %s", oid, e)
            self._complete(oid, {"__error__": f"payload decode failed: {e}"})
            return None
        req = Request(
            model=meta["model"],
            payload=payload,
            slo_ms=float(meta["slo_ms"]),
            request_id=meta["request_id"],
            # preserve the frontend's submit time so queue-wait inside the
            # shm ring counts against the SLO (staleness + accounting)
            arrival_ms=float(meta.get("ts_ms") or now_ms()),
        )

        def _on_done(fut) -> None:
            err = fut.exception()
            if err is not None:
                self._complete(oid, {"__error__": str(err)})
            else:
                result = fut.result()
                if not isinstance(result, np.ndarray):
                    try:
                        json.dumps(result)
                    except TypeError:
                        result = {"repr": repr(result)}
                self._complete(oid, result)

        req.future.add_done_callback(_on_done)
        return req

    def pump_once(self, timeout_ms: int = 100) -> int:
        """One batch-pop + submit sweep; returns requests pumped."""
        items = self.queue.pop_batch(self.batch_size, timeout_ms=timeout_ms)
        n = 0
        for raw in items:
            try:
                meta = json.loads(raw)
                req = self._make_request(meta)
            except Exception as e:  # noqa: BLE001 — poison pill must not kill the pump
                logger.warning("bad shm request: %s", e)
                self.errors += 1
                continue
            if req is None:
                self.errors += 1
                continue
            if not self.submit(req):
                req.reject(RuntimeError("engine rejected request"))
                self.errors += 1
                continue  # rejected != pumped: throughput stays honest
            n += 1
        self.pumped += n
        return n

    def _loop(self) -> None:
        while self._run.is_set():
            self.pump_once()

    def start(self) -> "ShmBridge":
        self._run.set()
        self._thread = threading.Thread(
            target=self._loop, name="shm-bridge", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, unlink: bool = True) -> None:
        self._run.clear()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a wedged submit callable still owns the handles: closing
                # them under the live loop would hand C a freed mapping
                # (segfault); leak instead and say so
                logger.error(
                    "shm bridge pump thread did not exit; leaking shm "
                    "handles %s to avoid use-after-free", self.frontend_name,
                )
                return
            self._thread = None
        self.queue.close(unlink)
        self.store.close(unlink)
