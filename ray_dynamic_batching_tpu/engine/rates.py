"""Sliding-window request-rate tracking.

Re-creates the reference's ``RequestTracker``
(``293-project/src/scheduler.py:115-169``: thread-safe requests/sec over a
window that resets after ``window_size``). Here the window slides smoothly —
per-second counts in a ring pruned on read — so the control loop never sees
the sawtooth a hard reset produces.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ray_dynamic_batching_tpu.utils.concurrency import assert_owner


class RateTracker:
    """Requests/sec over a sliding window (one instance per model)."""

    def __init__(self, window_s: float = 10.0, clock=time.monotonic):
        self.window_s = window_s
        self._clock = clock
        self._buckets: Deque[Tuple[int, int]] = deque()  # (second, count)
        self._total = 0
        self._lock = threading.Lock()

    def record(self, n: int = 1) -> None:
        sec = int(self._clock())
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                s, c = self._buckets[-1]
                self._buckets[-1] = (s, c + n)
            else:
                self._buckets.append((sec, n))
            self._total += n
            self._prune(sec)

    def _prune(self, now_sec: int) -> None:
        assert_owner(self._lock)  # callers hold it (record / rate_rps)
        cutoff = now_sec - int(self.window_s)
        while self._buckets and self._buckets[0][0] <= cutoff:
            _, c = self._buckets.popleft()
            self._total -= c

    def rate_rps(self) -> float:
        sec = int(self._clock())
        with self._lock:
            self._prune(sec)
            if not self._buckets:
                return 0.0
            # Use the actual covered span so a cold start doesn't under-read.
            span = max(1.0, min(self.window_s, sec - self._buckets[0][0] + 1))
            return self._total / span

    def span_s(self) -> float:
        """Seconds of window the estimate actually covers (0 = cold).
        A 2-second-old tracker extrapolates one arrival to a full rate —
        change detectors should know how much evidence backs the number."""
        sec = int(self._clock())
        with self._lock:
            self._prune(sec)
            if not self._buckets:
                return 0.0
            return min(self.window_s, sec - self._buckets[0][0] + 1)

    def count_between(self, t0_s: float, t1_s: float) -> Optional[int]:
        """Arrivals recorded in ``[t0_s, t1_s)``, or None when the
        sliding window has already rotated past ``t0_s`` — the truth is
        gone, and a partial count would read as a real (low) rate.
        Forecast scoring uses this to grade a prediction against what
        ACTUALLY arrived over its horizon."""
        sec = int(self._clock())
        lo, hi = int(t0_s), int(t1_s)
        with self._lock:
            self._prune(sec)
            if lo <= sec - int(self.window_s):
                return None
            return sum(c for s, c in self._buckets if lo <= s < hi)

    def forecast_rps(self, horizon_s: float, alpha: float = 0.5,
                     beta: float = 0.2,
                     min_span_s: float = 0.0) -> Optional[float]:
        """Short-horizon arrival forecast (requests/sec ``horizon_s``
        from now) via Holt's linear method — EWMA level + trend over the
        per-second buckets. Pure arithmetic over data already held: no
        randomness, no state kept between calls, jax-free.

        REFUSES (returns None) rather than extrapolating when the
        evidence is thin: an empty window, a covered span below
        ``min_span_s``, or fewer than two CLOSED seconds of history.
        The current partial second is always excluded — it under-reads
        by construction (the cold-window foot-gun ``rate_rps`` guards
        with its span floor)."""
        now_sec = int(self._clock())
        with self._lock:
            self._prune(now_sec)
            if not self._buckets:
                return None
            span = min(self.window_s, now_sec - self._buckets[0][0] + 1)
            if span < min_span_s:
                return None
            counts = dict(self._buckets)
            first = self._buckets[0][0]
        last_closed = now_sec - 1
        if last_closed - first < 1:
            return None
        # Contiguous per-second series, gaps are genuine zeros.
        series = [float(counts.get(s, 0))
                  for s in range(first, last_closed + 1)]
        level, trend = series[0], 0.0
        for x in series[1:]:
            prev = level
            level = alpha * x + (1.0 - alpha) * (level + trend)
            trend = beta * (level - prev) + (1.0 - beta) * trend
        return max(0.0, level + trend * float(horizon_s))


class RateRegistry:
    """Per-model trackers + significant-change detection for the control loop
    (ref: threshold test at scheduler.py:794-801 — 5% change triggers a
    reschedule, doubled for decreases)."""

    def __init__(self, window_s: float = 10.0, clock=time.monotonic):
        self.window_s = window_s
        self._clock = clock
        self._trackers: Dict[str, RateTracker] = {}
        self._last_scheduled: Dict[str, float] = {}
        self._lock = threading.Lock()

    def tracker(self, model: str) -> RateTracker:
        with self._lock:
            if model not in self._trackers:
                self._trackers[model] = RateTracker(self.window_s, self._clock)
            return self._trackers[model]

    def record(self, model: str, n: int = 1) -> None:
        self.tracker(model).record(n)

    def rates(self) -> Dict[str, float]:
        with self._lock:
            items = list(self._trackers.items())
        return {m: t.rate_rps() for m, t in items}

    def changed_models(
        self, threshold: float, decrease_multiplier: float = 2.0,
        min_span_s: float = 0.0,
    ) -> Dict[str, float]:
        """Models whose rate moved beyond the threshold since the last
        accepted schedule; increases trip at `threshold`, decreases at
        `threshold * decrease_multiplier` (asymmetric — scaling down too
        eagerly causes flapping, ref scheduler.py:794-801).

        ``min_span_s`` ignores models whose sliding window covers less
        than that many seconds: a cold tracker extrapolates its first
        arrivals to up-to-2x-inflated rates, and replanning on that
        evidence migrates engines for noise (observed: a colocation demo
        split chips at t=5s on a 2.0 reading of a true 1.0 tok/s). Two
        exemptions: a model with NO scheduled baseline (its first
        scale-up has no engine to migrate, and holding its traffic
        unserved for half a window is guaranteed SLO misses), and an
        EMPTY window (span 0 means traffic stopped and the buckets
        expired — a real scale-to-zero signal, not a cold start; a
        guard there would pin the idle model's engine in HBM forever)."""
        out: Dict[str, float] = {}
        for model, rate in self.rates().items():
            base = self._last_scheduled.get(model)
            if min_span_s > 0 and base:
                span = self.tracker(model).span_s()
                if 0 < span < min_span_s:
                    continue
            if base is None:
                if rate > 0:
                    out[model] = rate
                continue
            if base == 0:
                if rate > 0:
                    out[model] = rate
                continue
            delta = (rate - base) / base
            if delta > threshold or -delta > threshold * decrease_multiplier:
                out[model] = rate
        return out

    def forecasts(self, horizon_s: float, alpha: float = 0.5,
                  beta: float = 0.2,
                  min_span_s: float = 0.0) -> Dict[str, Optional[float]]:
        """Per-model ``forecast_rps``; a refusing tracker stays in the
        map as None so consumers can COUNT refusals instead of silently
        seeing fewer models (the observatory's never-silent rule)."""
        with self._lock:
            items = list(self._trackers.items())
        return {
            model: t.forecast_rps(horizon_s, alpha=alpha, beta=beta,
                                  min_span_s=min_span_s)
            for model, t in items
        }

    def mark_scheduled(self, rates: Optional[Dict[str, float]] = None) -> None:
        self._last_scheduled.update(rates if rates is not None else self.rates())

    def scheduled_rates(self) -> Dict[str, float]:
        return dict(self._last_scheduled)
