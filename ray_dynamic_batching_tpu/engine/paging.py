"""Paged-KV bookkeeping: free-list page allocator + copy-on-write stores.

Host-side half of the paged KV cache (ISSUE 7 / ROADMAP open item 1).
The DEVICE half is a fixed pool of lane-aligned HBM pages
(``models/decoder.py::PagedKVCache``: k/v ``[L, P, page_size, K, H]``)
gathered through per-slot page tables; THIS module owns which pages
belong to whom:

- :class:`PageAllocator` — a free list with refcounts. A page is either
  free (refcount 0, on the list) or held by 1+ owners; ``decref``
  returns it to the list only when the last owner lets go. Conservation
  (``free + allocated == num_pages``) is an invariant the allocator can
  assert about itself at any point (``check()``), and the property test
  drives 10k random op sequences against it.
- :class:`PagedPrefixCache` — page-granular prompt-prefix reuse: every
  FULL page of an admitted prompt is published under the hash of the
  token prefix it covers, so a later prompt shares its *longest common
  page-prefix* (vLLM's prefix tree, rendered static-shape: sharing is
  whole pages, the partial boundary page is copied — that copy IS the
  copy-on-write, performed at admission where the divergence point is
  already known because decode only ever appends).
- :class:`PagedSessionCache` — multi-turn continuation by reference:
  storing a finished turn pins the slot's pages (an incref) instead of
  copying the KV row out, so session residency costs ~zero extra HBM
  and store is O(1). Eviction drops only the cache's own ref — pages
  still shared into an active slot survive until that slot finishes
  (the evict-while-pinned rule the regression test pins).

Deliberately jax-free (numpy only): allocator invariants are tested at
pure-Python speed, and ``sim/`` can price page occupancy from the same
arithmetic without an accelerator stack.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_dynamic_batching_tpu.ops.tile_math import pages_for


def digest_chain(prompt: np.ndarray, page_size: int,
                 max_n: Optional[int] = None) -> List[bytes]:
    """Chained page-digest keys for ``prompt``: ``keys[j-1]`` covers
    pages ``[0, j)`` and is ``blake2b(page_j_tokens + keys[j-2])`` — one
    O(L) pass over the prompt bytes, 16 bytes retained per level.

    This is THE prefix identity of the whole stack: the per-engine
    :class:`PagedPrefixCache` keys its entries with it, the host-RAM
    spill tier keys spilled page runs with it, and the router's digest
    directory matches request prompts against replica publications with
    it — one function, so the three can never disagree on what "same
    prefix" means."""
    if max_n is None:
        max_n = int(prompt.size) // int(page_size)
    keys: List[bytes] = []
    prev = b""
    ps = int(page_size)
    for n in range(1, max_n + 1):
        page = np.ascontiguousarray(prompt[(n - 1) * ps: n * ps]).tobytes()
        prev = hashlib.blake2b(page + prev, digest_size=16).digest()
        keys.append(prev)
    return keys


class OutOfPages(Exception):
    """The pool cannot supply the requested pages (over-subscribed KV
    pool under load). The engine's policy on this is documented at the
    raise site — never silent."""


class PageEventJournal:
    """Bounded ring of allocator events — the paged pool's flight
    recorder. Placement and paging decisions (allocs, EOS frees, CoW
    borrows, cache-pin reclaims, capacity evictions, speculative
    splice-commits/reject-frees) spend milliseconds
    that are invisible between a decode-turn span's start and end; the
    journal stamps each one with the SAME monotonic-ms clock the tracer
    uses, so ``utils/trace_export.py`` renders them as Perfetto instant
    events + a page-occupancy counter track time-aligned with the spans.

    Bounded (ring) but never silent about it: ``total`` counts every
    event ever recorded, so ``total - len(ring)`` is exactly how many
    rotated out. Thread-compat: the decode engine records from its own
    single thread; ``snapshot()`` copies under the GIL (deque slicing is
    atomic enough for a monitoring read).
    """

    KINDS = ("alloc", "free", "cow_copy", "cache_reclaim", "eviction",
             "spill", "reload", "spec_commit", "spec_reject",
             "migrate_out", "migrate_in", "push_out", "push_in")

    def __init__(self, capacity: int = 4096) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.total = 0

    def record(self, kind: str, pages: int, pages_in_use: int,
               t_ms: Optional[float] = None, **detail) -> None:
        if kind not in self.KINDS:
            raise ValueError(
                f"unknown journal event kind {kind!r} (known: {self.KINDS})"
            )
        if t_ms is None:
            import time

            t_ms = time.monotonic() * 1000.0
        ev = {"t_ms": float(t_ms), "kind": kind, "pages": int(pages),
              "pages_in_use": int(pages_in_use)}
        ev.update(detail)
        self._ring.append(ev)
        self.total += 1

    def snapshot(self) -> List[dict]:
        return list(self._ring)

    @property
    def rotated_out(self) -> int:
        return self.total - len(self._ring)

    def __len__(self) -> int:
        return len(self._ring)


class PageAllocator:
    """Fixed pool of KV pages: free list + per-page refcounts.

    Allocation is all-or-nothing (a half-allocated prompt is useless and
    would leak on the error path). ``incref`` adds an owner to an
    already-held page (prefix/session sharing); ``decref`` removes one
    and frees the page when the count hits zero. FIFO reuse (a deque,
    not a LIFO stack) maximizes the time a freed page's contents stay
    intact — harmless either way for correctness (pages are always
    fully rewritten before they are attended), but it makes
    use-after-free bugs loud in tests instead of accidentally reading
    fresh identical data.
    """

    def __init__(self, num_pages: int,
                 journal: Optional[PageEventJournal] = None):
        if num_pages <= 0:
            raise ValueError(f"num_pages must be positive, got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: collections.deque = collections.deque(
            range(self.num_pages)
        )
        self.refcount: List[int] = [0] * self.num_pages
        # Optional event journal: alloc/free are recorded HERE (the one
        # place that knows them); semantic events (CoW borrows, cache
        # reclaims, capacity evictions) are recorded by the engine at
        # their decision sites.
        self.journal = journal

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return self.num_pages - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` fresh pages (refcount 1 each); all-or-nothing."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages, {len(self._free)}/{self.num_pages} free"
            )
        out = [self._free.popleft() for _ in range(n)]
        for p in out:
            self.refcount[p] = 1
        if self.journal is not None and out:
            self.journal.record("alloc", len(out), self.allocated_pages)
        return out

    def incref(self, pages: Sequence[int]) -> None:
        """Add an owner to pages that are already held (sharing). An
        incref of a FREE page is a bug (its contents are reusable by
        anyone) — refuse loudly rather than resurrect it."""
        for p in pages:
            if self.refcount[p] <= 0:
                raise ValueError(
                    f"incref of free page {p} — share must happen while "
                    "the original owner still holds it"
                )
        for p in pages:
            self.refcount[p] += 1

    def decref(self, pages: Sequence[int]) -> List[int]:
        """Drop one ownership per page; returns the pages actually freed
        (refcount reached zero — back on the free list)."""
        freed: List[int] = []
        for p in pages:
            if self.refcount[p] <= 0:
                raise ValueError(f"decref of free page {p} (double free)")
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)
                freed.append(p)
        if self.journal is not None and freed:
            self.journal.record("free", len(freed), self.allocated_pages)
        return freed

    def check(self) -> None:
        """Assert the conservation invariants (cheap; tests call it
        after every op of the random 10k-op sequence):

        - free + allocated == num_pages, with no page on the free list
          twice;
        - refcount is never negative;
        - a page is on the free list iff its refcount is zero.
        """
        free = list(self._free)
        if len(set(free)) != len(free):
            raise AssertionError(f"free list holds duplicates: {free}")
        if len(free) + self.allocated_pages != self.num_pages:
            raise AssertionError(
                f"conservation broken: {len(free)} free + "
                f"{self.allocated_pages} allocated != {self.num_pages}"
            )
        free_set = set(free)
        for p, rc in enumerate(self.refcount):
            if rc < 0:
                raise AssertionError(f"page {p} refcount {rc} < 0")
            if (rc == 0) != (p in free_set):
                raise AssertionError(
                    f"page {p} refcount {rc} but "
                    f"{'on' if p in free_set else 'off'} the free list"
                )


class _PinnedLRU:
    """Bounded LRU whose values hold PINNED page ids: insertion increfs,
    eviction/replacement decrefs — the cache's own reference, distinct
    from any slot's. Shared mechanics for the prefix and session stores
    so pin/unpin symmetry cannot diverge between them."""

    def __init__(self, capacity: int, allocator: PageAllocator):
        self.capacity = int(capacity)
        self.allocator = allocator
        self._entries: "collections.OrderedDict" = collections.OrderedDict()

    def _pages_of(self, value) -> Sequence[int]:
        raise NotImplementedError

    def evict_lru(self) -> bool:
        """Drop the least-recently-used entry (page-pressure reclaim:
        cache pins are optimizations, and under pool pressure the engine
        sheds them before truncating live streams). Returns False when
        empty. Note the decref may free nothing if a borrower still
        holds the pages — the caller loops."""
        if not self._entries:
            return False
        _, evicted = self._entries.popitem(last=False)
        self.allocator.decref(self._pages_of(evicted))
        return True

    def peek_lru(self):
        """(key, value) of the entry :meth:`evict_lru` would drop next,
        or None — the spill tier reads the victim's pages BEFORE the
        eviction releases the cache's pin on them."""
        if not self._entries:
            return None
        key = next(iter(self._entries))
        return key, self._entries[key]

    def _get(self, key):
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        return entry

    def _put(self, key, value) -> None:
        old = self._entries.pop(key, None)
        if old is not None:
            self.allocator.decref(self._pages_of(old))
        self.allocator.incref(self._pages_of(value))
        self._entries[key] = value
        while len(self._entries) > self.capacity:
            _, evicted = self._entries.popitem(last=False)
            # Evict-while-pinned: this drops ONLY the cache's ref. Pages
            # still shared into a live slot keep that slot's refcount and
            # stay resident until it finishes — freeing them here would
            # hand an in-use page to the next admission (the refcount
            # leak class the regression test pins).
            self.allocator.decref(self._pages_of(evicted))

    def clear(self) -> None:
        while self._entries:
            _, evicted = self._entries.popitem(last=False)
            self.allocator.decref(self._pages_of(evicted))

    def __len__(self) -> int:
        return len(self._entries)


class PagedPrefixCache(_PinnedLRU):
    """Page-granular prompt-prefix index (the paged successor of the
    chunk-granular ``decode.PrefixCache``).

    Insertion publishes EVERY full-page prefix of an admitted prompt:
    level ``j`` is keyed by a digest CHAIN — level j's key is
    ``blake2b(page_j_tokens + key_{j-1})`` — so computing all L/ps level
    keys of a prompt costs one O(L) pass (each token byte is hashed
    once), not the O(L^2/ps) of re-serializing every prefix, and the
    store retains 16-byte digests instead of whole prefix byte-strings.
    Lookup probes from the longest possible level down, so a hit is the
    *longest shared page-prefix* — byte-equality of whole prompts is no
    longer required (satellite: page-granular keying). A hit must leave
    >= 1 token to prefill (the tail drives the first sampled logits),
    hence the strict ``< prompt_len`` bound.
    """

    def __init__(self, capacity: int, page_size: int,
                 allocator: PageAllocator):
        super().__init__(capacity, allocator)
        self.page_size = int(page_size)
        # Per-entry hit counts (bumped on lookup hits at the level that
        # matched): the push-replication planner's hotness ranking —
        # ``hot()`` orders what THIS pool can export by demand actually
        # observed here. Bounded lazily against 4x capacity so counters
        # of long-evicted entries cannot accumulate forever.
        self._hits: Dict[bytes, int] = {}

    def _pages_of(self, value) -> Sequence[int]:
        return value

    def _level_keys(self, prompt: np.ndarray, max_n: int) -> List[bytes]:
        """Chained level keys: keys[j-1] covers pages [0, j). One pass
        over the prompt bytes total (module-level :func:`digest_chain` —
        shared with the spill tier and the router's digest directory)."""
        return digest_chain(prompt, self.page_size, max_n)

    def digests(self, limit: int = 128) -> Dict[str, int]:
        """Bounded digest publication for cluster-wide prefix routing:
        the ``limit`` most-recently-used entries as ``{digest_hex:
        chain_len}``. O(1) per entry (the 16-byte level key IS the
        identity — no token bytes leave the replica), recency-bounded so
        a replica advertises what its pool actually still holds."""
        out: Dict[str, int] = {}
        for key in reversed(self._entries):
            if len(out) >= limit:
                break
            out[key.hex()] = len(self._entries[key])
        return out

    def lookup(self, prompt: np.ndarray) -> Optional[Tuple[List[int], int]]:
        """Longest shared page-prefix: ``(page_ids, shared_len)`` with
        ``shared_len == len(page_ids) * page_size < prompt.size``, or
        None."""
        max_n = (int(prompt.size) - 1) // self.page_size
        keys = self._level_keys(prompt, max_n)
        for n in range(max_n, 0, -1):
            entry = self._get(keys[n - 1])
            if entry is not None:
                key = keys[n - 1]
                self._hits[key] = self._hits.get(key, 0) + 1
                if len(self._hits) > 4 * self.capacity:
                    self._hits = {k: v for k, v in self._hits.items()
                                  if k in self._entries}
                return list(entry), n * self.page_size
        return None

    def insert(self, prompt: np.ndarray, page_ids: Sequence[int]) -> None:
        """Publish every full-page prefix of ``prompt`` whose pages are
        in ``page_ids`` (the admitting slot's table, still held by the
        slot — incref happens per level inside ``_put``)."""
        n_full = min(int(prompt.size) // self.page_size, len(page_ids))
        for n, key in enumerate(self._level_keys(prompt, n_full), start=1):
            if key not in self._entries:
                self._put(key, tuple(page_ids[:n]))

    def install(self, key: bytes, page_ids: Sequence[int]) -> bool:
        """Publish ONE entry under a pre-computed digest ``key`` — the
        fabric-push install path. A peer replica ships pages addressed
        by the chain digest alone (16 bytes; token bytes never leave
        their replica), so the receiver cannot recompute level keys —
        it trusts the digest the way the router's directory already
        does. ``page_ids`` must be held by the caller (refcount >= 1);
        ``_put`` increfs the cache's own pin, the caller then drops its
        hold — pin symmetry identical to a spill reload republishing.
        Returns False (and pins nothing) when the key is already
        present — a duplicate push refreshes recency instead."""
        if key in self._entries:
            self._get(key)
            return False
        self._put(key, tuple(page_ids))
        return True

    def hot(self, limit: int = 8) -> List[Tuple[str, int, int]]:
        """The ``limit`` hottest RESIDENT entries as ``(digest_hex,
        chain_len, hits)``, hit-rank ordered, zero-hit entries elided —
        what the push planner considers worth replicating from here."""
        ranked = sorted(
            (k for k in self._entries if self._hits.get(k, 0) > 0),
            key=lambda k: -self._hits.get(k, 0),
        )
        return [(k.hex(), len(self._entries[k]), self._hits.get(k, 0))
                for k in ranked[:limit]]


class PagedSessionCache(_PinnedLRU):
    """Session-id -> pinned page run of the finished turn.

    ``store`` pins the pages covering the stored history instead of
    copying the KV row out of the cache (the slab SessionCache's
    per-turn full-row device copy disappears); ``lookup`` returns the
    page run + history length when the stored turn is a strict prefix
    of the next prompt, exactly the slab semantics."""

    def __init__(self, capacity: int, page_size: int,
                 allocator: PageAllocator):
        super().__init__(capacity, allocator)
        self.page_size = int(page_size)

    def _pages_of(self, value) -> Sequence[int]:
        return value[0]

    def lookup(self, session_id: str, prompt: np.ndarray
               ) -> Optional[Tuple[List[int], int]]:
        """``(page_ids, stored_len)`` when the stored turn strictly
        prefixes ``prompt`` (>= 1 tail token left to prefill)."""
        entry = self._get(session_id)
        if entry is None:
            return None
        pages, history = entry
        n = int(history.size)
        if n >= prompt.size or not np.array_equal(history, prompt[:n]):
            return None
        return list(pages), n

    def store(self, session_id: str, page_ids: Sequence[int],
              history: np.ndarray) -> None:
        """Pin the pages covering ``history`` under ``session_id``.
        Call while the finishing slot still holds its pages (incref
        before the slot's decref — the pages must never transit
        refcount 0)."""
        n = pages_for(int(history.size), self.page_size)
        self._put(session_id,
                  (tuple(page_ids[:n]), np.asarray(history, np.int32)))


class HostSpillTier:
    """HBM → host-RAM eviction tier for prefix pages (ISSUE 11).

    When pool pressure sheds a prefix-cache pin, the entry's page
    CONTENTS move to host RAM (keyed by the same chained digest as the
    HBM entry) instead of vanishing — a later prompt sharing that prefix
    reloads the pages into freshly allocated HBM and skips the prefill
    recompute. Hot system prompts therefore survive pool churn AND
    replica churn: the digest keys a replica publishes to the router
    include its spilled entries, so cluster-wide prefix routing keeps
    steering matching prompts here.

    Page IO is injected (``read_pages(page_ids) -> payload``,
    ``write_pages(page_ids, payload)``) so this stays numpy-only and
    testable without a device; the engine binds them to gather/scatter
    on its device page pool. Every spill and reload is journaled like
    any other allocator event — the tier is part of the page pool's
    flight record, not a side channel.

    Bounded by ``capacity_pages`` of host residency, LRU within the
    bound. An entry is REMOVED on reload (its pages are back in HBM and
    the prefix cache re-publishes them); re-spilling on the next
    pressure wave re-reads the then-current contents.
    """

    def __init__(
        self,
        capacity_pages: int,
        read_pages: Callable[[List[int]], Dict[str, np.ndarray]],
        write_pages: Callable[[List[int], Dict[str, np.ndarray]], None],
        journal: Optional[PageEventJournal] = None,
    ) -> None:
        if capacity_pages <= 0:
            raise ValueError(
                f"capacity_pages must be positive, got {capacity_pages}"
            )
        self.capacity_pages = int(capacity_pages)
        self._read = read_pages
        self._write = write_pages
        self.journal = journal
        # digest key (bytes) -> (payload, n_pages), LRU order.
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self.pages_held = 0
        self.spills = 0
        self.reloads = 0
        self.dropped = 0  # entries LRU-evicted from the tier itself
        # Digests whose pages came BACK from host RAM since the last
        # publication drain. A reload moves the entry between tiers
        # without changing the union the replica advertises, so the
        # directory's replacement-expiry sees "unchanged" and skips the
        # long-poll notify — out-of-process routers would never converge
        # after a spill round-trip. The controller drains this via
        # ``prefix_digests`` and forces the push.
        self._republish: List[str] = []

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def spill(self, key: bytes, page_ids: Sequence[int],
              pages_in_use: int) -> bool:
        """Copy ``page_ids``' contents to host under ``key``. Call
        BEFORE the HBM eviction drops the pin (the pages must still be
        intact). Returns False when the key is already spilled (the
        caller may proceed straight to the eviction)."""
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        n = len(page_ids)
        if n > self.capacity_pages:
            return False  # one oversized entry cannot fit; don't thrash
        payload = self._read(list(page_ids))
        self._entries[key] = (payload, n)
        self.pages_held += n
        self.spills += 1
        if self.journal is not None:
            self.journal.record("spill", n, pages_in_use,
                                digest=key.hex())
        while self.pages_held > self.capacity_pages:
            _, (_, n_drop) = self._entries.popitem(last=False)
            self.pages_held -= n_drop
            self.dropped += 1
        return True

    def reload(self, key: bytes,
               allocator: PageAllocator) -> Optional[List[int]]:
        """Allocate fresh pages and copy the spilled contents back into
        HBM; returns the page ids (refcount 1, owned by the caller) or
        None when the key is absent or the pool cannot supply the pages
        right now (the caller falls back to recompute — a reload must
        never deepen the pressure that caused the spill)."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        payload, n = entry
        if not allocator.can_alloc(n):
            return None
        page_ids = allocator.alloc(n)
        self._write(page_ids, payload)
        del self._entries[key]
        self.pages_held -= n
        self.reloads += 1
        self._republish.append(key.hex())
        if self.journal is not None:
            self.journal.record("reload", n, allocator.allocated_pages,
                                digest=key.hex())
        return page_ids

    def drain_republish(self) -> List[str]:
        """Digests reloaded since the last drain (cleared on read): the
        cluster-wide republish signal the controller's digest push path
        consumes — see ``_republish``'s note on why tier moves must
        force a directory notify even though the advertised set is
        unchanged."""
        out, self._republish = self._republish, []
        return out

    def digests(self, limit: int = 128) -> Dict[str, int]:
        """Spilled entries as ``{digest_hex: chain_len}`` — published to
        the router alongside the HBM prefix cache's digests, because a
        spilled prefix is still servable here (one reload vs a full
        prefill recompute elsewhere)."""
        out: Dict[str, int] = {}
        for key in reversed(self._entries):
            if len(out) >= limit:
                break
            out[key.hex()] = self._entries[key][1]
        return out

    def clear(self) -> None:
        self._entries.clear()
        self.pages_held = 0
        self._republish.clear()

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries),
                "pages_held": self.pages_held,
                "spills": self.spills, "reloads": self.reloads,
                "dropped": self.dropped}


def table_array(pages: Sequence[int], n_entries: int,
                sentinel: int) -> np.ndarray:
    """A slot's page list as a fixed-width int32 row for the device
    table: unallocated tail entries carry ``sentinel`` (= pool size, one
    past the last valid page) so device-side writes through them DROP
    and gathers clamp into masked-off territory."""
    out = np.full((n_entries,), sentinel, dtype=np.int32)
    k = min(len(pages), n_entries)
    out[:k] = pages[:k]
    return out
